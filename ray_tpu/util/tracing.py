"""Tracing hooks (parity: ``python/ray/util/tracing/tracing_helper.py``).

The reference patches every remote call with OpenTelemetry spans when
``ray.init(_tracing_startup_hook=...)`` is set.  Here tracing is a
light seam over the same points: if ``opentelemetry`` is importable the
spans are real OTel spans (exported by whatever provider the user
configured); otherwise an in-process recorder keeps (name, start, end,
attributes) tuples so tests and the timeline can still observe the
graph.  Zero overhead when never enabled.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_enabled = False
_tracer = None          # otel tracer when available
_records: List[Dict[str, Any]] = []   # fallback recorder
_MAX_RECORDS = 10_000


def enable_tracing() -> bool:
    """Turn on span emission; True if real OpenTelemetry is active.

    The flag is process-local, so it is ALSO published to the control
    plane: worker processes check it at startup (``worker_proc``) and
    emit execute-side spans.  Workers already running before the enable
    keep tracing off until restarted (same init-time contract as the
    reference's ``_tracing_startup_hook``)."""
    _publish("1")
    global _enabled, _tracer
    with _lock:
        _enabled = True
        if _tracer is None:
            try:
                from opentelemetry import trace as otel_trace

                # only route spans to OTel when the user actually
                # configured a provider — the library default
                # (ProxyTracerProvider with no SDK behind it) swallows
                # spans silently, which would also starve the
                # in-process recorder that tests and the timeline read
                provider = otel_trace.get_tracer_provider()
                if type(provider).__name__ not in (
                        "ProxyTracerProvider", "NoOpTracerProvider"):
                    _tracer = otel_trace.get_tracer("ray_tpu")
            except Exception:  # noqa: BLE001 — recorder fallback
                _tracer = None
        return _tracer is not None


def disable_tracing() -> None:
    global _enabled
    _publish("0")
    with _lock:
        _enabled = False


_KV_KEY = b"__ray_tpu_tracing__"


def _publish(val: str) -> None:
    """Best-effort cluster-wide flag (no-op outside a ray_tpu session)."""
    try:
        from ray_tpu._private.worker import global_worker
        global_worker().cp.kv_put(_KV_KEY, val.encode(), True, "_sys")
    except Exception:  # noqa: BLE001 — local-only tracing still works
        pass


_cluster_cp = None
_cluster_checked = 0.0
_CLUSTER_TTL_S = 5.0


def maybe_enable_from_cluster(cp) -> None:
    """Worker-startup hook: adopt (and keep polling, via the TTL check
    in :func:`_refresh`) the cluster-wide tracing flag."""
    global _cluster_cp
    _cluster_cp = cp
    _refresh(force=True)


def _refresh(force: bool = False) -> None:
    """Re-read the cluster flag at most every ``_CLUSTER_TTL_S`` so an
    ``enable_tracing()`` on the driver reaches already-running workers
    within seconds (one KV read per worker per TTL — off the hot path
    unless tracing state actually changes anything)."""
    global _enabled, _cluster_checked
    if _cluster_cp is None:
        return
    now = time.monotonic()
    if not force and now - _cluster_checked < _CLUSTER_TTL_S:
        return
    _cluster_checked = now
    try:
        val = _cluster_cp.kv_get(_KV_KEY, namespace="_sys")
    except Exception:  # noqa: BLE001
        return
    if val == b"1" and not _enabled:
        with _lock:
            _enabled = True
    elif val == b"0" and _enabled:
        with _lock:
            _enabled = False


def is_enabled() -> bool:
    return _enabled


def recorded_spans() -> List[Dict[str, Any]]:
    """Fallback-recorder contents (OTel-less environments/tests)."""
    with _lock:
        return list(_records)


def clear_recorded() -> None:
    with _lock:
        _records.clear()


@contextlib.contextmanager
def span(name: str, **attributes):
    """Trace one operation.  No-op (two attr reads) when disabled.

    The fallback record keeps an *epoch* ``start`` for timeline
    placement but computes ``dur`` (and the derived ``end``) from the
    monotonic clock: ``time.time()`` can step backwards under NTP
    slew, which used to yield negative/garbage durations for spans
    straddling a clock adjustment."""
    if not _enabled:
        yield None
        return
    if _tracer is not None:
        with _tracer.start_as_current_span(name) as s:
            for k, v in attributes.items():
                try:
                    s.set_attribute(k, v)
                except Exception:  # noqa: BLE001
                    pass
            yield s
        return
    rec = {"name": name, "start": time.time(),
           "tid": threading.get_ident(), "attributes": attributes}
    t0 = time.monotonic()
    try:
        yield rec
    finally:
        rec["dur"] = time.monotonic() - t0
        rec["end"] = rec["start"] + rec["dur"]
        with _lock:
            _records.append(rec)
            if len(_records) > _MAX_RECORDS:
                del _records[:len(_records) - _MAX_RECORDS]


def task_span(spec) -> "contextlib.AbstractContextManager":
    """Span for one task/actor-method execution (worker side)."""
    _refresh()
    if not _enabled:
        return contextlib.nullcontext()
    return span(
        f"task::{getattr(spec, 'name', '?')}",
        task_id=getattr(spec, 'task_id', b'').hex()[:16],
        actor_method=getattr(spec, 'actor_method', None) or "",
    )


def submit_span(name: str) -> "contextlib.AbstractContextManager":
    """Span for a submission on the caller side."""
    if not _enabled:
        return contextlib.nullcontext()
    return span(f"submit::{name}")
