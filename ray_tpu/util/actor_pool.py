"""ActorPool (parity: ``python/ray/util/actor_pool.py``)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    """Round-robins work over a fixed set of actors.

    >>> pool = ActorPool([a1, a2])
    >>> list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    """

    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits = []

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable, value: Any):
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def get_next(self, timeout: float = None) -> Any:
        if not self.has_next():
            raise StopIteration("no more results")
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        index, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        return ray_tpu.get(future, timeout=timeout)

    def get_next_unordered(self, timeout: float = None) -> Any:
        if not self.has_next():
            raise StopIteration("no more results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        index, actor = self._future_to_actor.pop(future)
        self._index_to_future.pop(index, None)
        self._return_actor(actor)
        return ray_tpu.get(future)

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor):
        self._idle.append(actor)
