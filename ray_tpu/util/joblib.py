"""joblib backend (parity: ``python/ray/util/joblib``).

``register_ray()`` then ``joblib.parallel_backend("ray_tpu")`` routes
scikit-learn-style ``Parallel(...)`` work through cluster tasks.
"""

from __future__ import annotations


def register_ray() -> None:
    from joblib import register_parallel_backend
    from joblib._parallel_backends import MultiprocessingBackend

    import ray_tpu

    @ray_tpu.remote
    def _run_batch(batch):
        return batch()  # joblib BatchedCalls is itself callable

    class RayTpuBackend(MultiprocessingBackend):
        """Submit joblib batches as cluster tasks."""

        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            if n_jobs == 1:
                return 1
            # advertise cluster CPU capacity
            try:
                from ray_tpu._private.worker import global_worker
                total = sum(
                    (n.get("resources_total") or {}).get("CPU", 0)
                    for n in global_worker().cp.list_nodes()
                    if n.get("state") == "ALIVE")
                return max(1, int(total))
            except Exception:  # noqa: BLE001
                return super().effective_n_jobs(n_jobs)

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **kwargs):
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def submit(self, func, callback=None):
            import threading
            ref = _run_batch.remote(func)

            class _Future:
                def get(self, timeout=None):
                    return ray_tpu.get(ref, timeout=timeout)

            if callback is not None:
                # joblib's completion accounting runs off the callback
                # (supports_retrieve_callback): fire it from a waiter
                # thread, passing the result — or the exception, which
                # retrieve_result_callback re-raises
                def waiter():
                    try:
                        out = ray_tpu.get(ref)
                    except BaseException as e:  # noqa: BLE001
                        out = e
                    callback(out)

                threading.Thread(target=waiter, daemon=True).start()
            return _Future()

        # pre-1.2 joblib name for submit()
        apply_async = submit

        @staticmethod
        def retrieve_result_callback(out):
            if isinstance(out, BaseException):
                raise out
            return out

        def terminate(self):
            pass

        def abort_everything(self, ensure_ready=True):
            if ensure_ready:
                self.configure(n_jobs=self.parallel.n_jobs,
                               parallel=self.parallel)

    register_parallel_backend("ray_tpu", RayTpuBackend)
