"""Placement groups — gang resource reservation.

Parity: ``python/ray/util/placement_group.py`` + the raylet bundle 2PC
(``PrepareBundleResources``/``CommitBundleResources``).  Bundles reserve
resources on nodes and expose them as ``pg_<id>_<index>_<resource>``
custom resources that PG-scheduled tasks/actors consume (the reference's
formatted-resource mechanism).

Strategies: PACK (prefer one node), SPREAD (prefer distinct nodes),
STRICT_PACK (must be one node), STRICT_SPREAD (must be distinct nodes).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.protocol import RpcClient
from ray_tpu._private.worker import global_worker


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID,
                 bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self):
        """ObjectRef resolving when the group is created (or failed)."""
        import ray_tpu

        pg_id = self.id

        @ray_tpu.remote(num_cpus=0)
        def _pg_ready():
            worker = global_worker()
            info = worker.cp.wait_placement_group(pg_id.binary(), 300.0)
            if info is None or info.get("state") != "CREATED":
                raise TimeoutError("placement group was not created")
            return True

        return _pg_ready.remote()

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        worker = global_worker()
        info = worker.cp.wait_placement_group(self.id.binary(),
                                              timeout_seconds)
        return bool(info and info.get("state") == "CREATED")

    def __reduce__(self):
        return (_rebuild_pg, (self.id.binary(), self.bundle_specs))


def _rebuild_pg(pg_id_bin: bytes, bundles):
    return PlacementGroup(PlacementGroupID(pg_id_bin), bundles)


def _nm_client_for(worker, node_info):
    if (worker.nm is not None
            and getattr(worker.nm, "sock_path", None)
            == node_info["sock_path"]):
        return worker.nm
    client = RpcClient(node_info["sock_path"])
    client.sock_path = node_info["sock_path"]
    return client


def _call(nm, method: str, *args):
    if hasattr(nm, "call"):
        return nm.call(method, *args)
    return getattr(nm, method)(*args)


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    lifetime: Optional[str] = None) -> PlacementGroup:
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b}")
    worker = global_worker()
    pg_id = PlacementGroupID.of(worker.job_id)
    worker.cp.register_placement_group(pg_id.binary(), {
        "bundles": bundles, "strategy": strategy, "name": name,
        "state": "PENDING",
    })
    pg = PlacementGroup(pg_id, bundles)
    # Reserve asynchronously so pending groups don't block the driver
    # (parity: GCS placement group manager retries until resources exist).
    t = threading.Thread(target=_reserve_loop,
                         args=(pg_id.binary(), bundles, strategy),
                         daemon=True, name="pg-reserve")
    t.start()
    return pg


def _reserve_loop(pg_id: bytes, bundles, strategy: str,
                  timeout: float = 300.0):
    worker = global_worker()
    deadline = time.time() + timeout
    while time.time() < deadline:
        if _try_reserve(worker, pg_id, bundles, strategy):
            worker.cp.update_placement_group(pg_id, state="CREATED")
            return
        time.sleep(0.2)
    worker.cp.update_placement_group(pg_id, state="FAILED")


def _try_reserve(worker, pg_id: bytes, bundles, strategy: str) -> bool:
    nodes = [n for n in worker.cp.list_nodes() if n["state"] == "ALIVE"]
    if not nodes:
        return False
    placements: List[Optional[dict]] = []
    from ray_tpu._private.task_spec import fits
    avail = {n["node_id"]: dict(n.get("resources_available", {}))
             for n in nodes}
    by_id = {n["node_id"]: n for n in nodes}

    def place(bundle, candidates):
        for nid in candidates:
            if fits(avail[nid], bundle):
                for k, v in bundle.items():
                    avail[nid][k] = avail[nid].get(k, 0) - v
                return nid
        return None

    node_ids = list(avail.keys())
    chosen: List[Optional[bytes]] = []
    if strategy in ("PACK", "STRICT_PACK"):
        for i, bundle in enumerate(bundles):
            order = ([chosen[0]] + node_ids) if chosen and chosen[0] \
                else node_ids
            nid = place(bundle, order)
            chosen.append(nid)
        if strategy == "STRICT_PACK" and len(
                {c for c in chosen if c}) > 1:
            return False
    elif strategy in ("SPREAD", "STRICT_SPREAD"):
        used = set()
        for bundle in bundles:
            fresh = [n for n in node_ids if n not in used]
            nid = place(bundle, fresh + ([] if strategy == "STRICT_SPREAD"
                                         else node_ids))
            chosen.append(nid)
            if nid:
                used.add(nid)
    else:
        raise ValueError(f"unknown strategy {strategy!r}")
    if any(c is None for c in chosen):
        return False
    # commit reservations; roll back on partial failure
    committed = []
    for index, (bundle, nid) in enumerate(zip(bundles, chosen)):
        nm = _nm_client_for(worker, by_id[nid])
        ok = _call(nm, "reserve_bundle", pg_id, index, bundle)
        if not ok:
            for done_index, done_nid, done_bundle in committed:
                nm2 = _nm_client_for(worker, by_id[done_nid])
                _call(nm2, "return_bundle", pg_id, done_index, done_bundle)
            return False
        committed.append((index, nid, bundle))
    worker.cp.update_placement_group(
        pg_id, bundle_nodes=[c.hex() for c in chosen])
    return True


def remove_placement_group(pg: PlacementGroup) -> None:
    worker = global_worker()
    info = worker.cp.get_placement_group(pg.id.binary())
    if not info:
        return
    nodes = {n["node_id"].hex(): n for n in worker.cp.list_nodes()}
    for index, (bundle, nid_hex) in enumerate(
            zip(info.get("bundles", []), info.get("bundle_nodes", []))):
        node = nodes.get(nid_hex)
        if node is None:
            continue
        nm = _nm_client_for(worker, node)
        try:
            _call(nm, "return_bundle", pg.id.binary(), index, bundle)
        except (OSError, ConnectionError):
            pass
    worker.cp.update_placement_group(pg.id.binary(), state="REMOVED")


def get_placement_group(name: str) -> Optional[PlacementGroup]:
    worker = global_worker()
    for info in worker.cp.list_placement_groups():
        if info.get("name") == name and info.get("state") != "REMOVED":
            return PlacementGroup(PlacementGroupID(info["pg_id"]),
                                  info.get("bundles", []))
    return None


def placement_group_table() -> List[dict]:
    worker = global_worker()
    out = []
    for info in worker.cp.list_placement_groups():
        out.append({
            "placement_group_id": info["pg_id"].hex(),
            "name": info.get("name", ""),
            "state": info.get("state"),
            "strategy": info.get("strategy"),
            "bundles": info.get("bundles", []),
        })
    return out
