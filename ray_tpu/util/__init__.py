"""``ray_tpu.util`` — utility APIs (parity: ``python/ray/util``)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (PlacementGroup,
                                          get_placement_group,
                                          placement_group,
                                          placement_group_table,
                                          remove_placement_group)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)

__all__ = [
    "ActorPool", "PlacementGroup", "placement_group",
    "remove_placement_group", "get_placement_group",
    "placement_group_table", "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]
