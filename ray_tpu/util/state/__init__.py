"""State API (parity: ``python/ray/util/state``): programmatic listing of
cluster entities, backed by the control plane tables.

Every ``list_*`` takes ``filters`` — ``(key, op, value)`` triples with
the reference's predicate set (``= != < <= > >= contains in``,
``util/state/common.py`` role) — and ``offset`` for pagination; rows
come back in stable order so ``offset``/``limit`` windows stitch.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.worker import global_worker

Filter = Tuple[str, str, Any]


def _cp():
    return global_worker().cp


def _match(row: Dict[str, Any], key: str, op: str, value: Any) -> bool:
    have = row.get(key)
    if op in ("=", "=="):
        return str(have) == str(value)
    if op == "!=":
        return str(have) != str(value)
    if op == "contains":
        return str(value) in str(have)
    if op == "in":
        if isinstance(value, (str, bytes)):
            # a bare string would be iterated per-character and match
            # nothing, silently — make the misuse loud
            raise TypeError(
                "'in' filter value must be a list/tuple/set of "
                f"candidates, got {type(value).__name__}")
        return str(have) in [str(v) for v in value]
    # ordered comparisons: numeric when both sides parse, else lexical
    try:
        a, b = float(have), float(value)  # type: ignore[arg-type]
    except (TypeError, ValueError):
        a, b = str(have), str(value)      # type: ignore[assignment]
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise ValueError(f"unsupported filter op {op!r}")


def _window(rows: List[Dict[str, Any]],
            filters: Optional[List[Filter]], limit: int,
            offset: int) -> List[Dict[str, Any]]:
    if filters:
        for key, op, value in filters:
            rows = [r for r in rows if _match(r, key, op, value)]
    return rows[offset:offset + limit]


def list_nodes(limit: int = 1000, filters: Optional[List[Filter]] = None,
               offset: int = 0) -> List[Dict[str, Any]]:
    out = []
    for info in _cp().list_nodes():
        out.append({
            "node_id": info["node_id"].hex(),
            "state": info["state"],
            "ip": info.get("ip"),
            "resources_total": info.get("resources_total", {}),
            "resources_available": info.get("resources_available", {}),
            "labels": info.get("labels", {}),
            "load": info.get("load", {}),
            "death_reason": info.get("death_reason", ""),
        })
    out.sort(key=lambda r: r["node_id"])
    return _window(out, filters, limit, offset)


def list_actors(limit: int = 1000, filters: Optional[List[Filter]] = None,
                offset: int = 0) -> List[Dict[str, Any]]:
    out = []
    for info in _cp().list_actors():
        row = {
            "actor_id": info["actor_id"].hex(),
            "class_name": info.get("class_name"),
            "state": info.get("state"),
            "name": info.get("name"),
            "pid": info.get("pid"),
            "node_id": (info.get("node_id").hex()
                        if info.get("node_id") else None),
            "num_restarts": info.get("num_restarts", 0),
        }
        out.append(row)
    out.sort(key=lambda r: r["actor_id"])
    return _window(out, filters, limit, offset)


def list_tasks(limit: int = 10000,
               filters: Optional[List[Filter]] = None,
               offset: int = 0) -> List[Dict[str, Any]]:
    events = _cp().list_task_events(limit=100000)
    latest: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        tid = ev.get("task_id")
        cur = latest.setdefault(tid, {"task_id": tid})
        cur["state"] = ev.get("state")
        if ev.get("name"):
            cur["name"] = ev["name"]
        if ev.get("node"):
            cur["node_id"] = ev["node"]
        cur.setdefault("events", []).append(
            {"state": ev.get("state"), "time": ev.get("time")})
    rows = sorted(latest.values(), key=lambda r: r["task_id"] or "")
    return _window(rows, filters, limit, offset)


def list_objects(limit: int = 10000,
                 filters: Optional[List[Filter]] = None,
                 offset: int = 0) -> List[Dict[str, Any]]:
    rows = _cp().list_objects()
    rows.sort(key=lambda r: str(r.get("object_id", "")))
    return _window(rows, filters, limit, offset)


def list_placement_groups(limit: int = 1000,
                          filters: Optional[List[Filter]] = None,
                          offset: int = 0) -> List[Dict[str, Any]]:
    out = []
    for info in _cp().list_placement_groups():
        out.append({
            "placement_group_id": info["pg_id"].hex(),
            "name": info.get("name", ""),
            "state": info.get("state"),
            "strategy": info.get("strategy"),
            "bundles": info.get("bundles", []),
        })
    out.sort(key=lambda r: r["placement_group_id"])
    return _window(out, filters, limit, offset)


def summarize_tasks() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for task in list_tasks():
        counts[task.get("state", "?")] = counts.get(
            task.get("state", "?"), 0) + 1
    return counts


def summarize_actors() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for actor in list_actors():
        counts[actor.get("state", "?")] = counts.get(
            actor.get("state", "?"), 0) + 1
    return counts


def summarize_objects() -> Dict[str, Any]:
    return _cp().objects_summary()
