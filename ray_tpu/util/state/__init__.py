"""State API (parity: ``python/ray/util/state``): programmatic listing of
cluster entities, backed by the control plane tables."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu._private.worker import global_worker


def _cp():
    return global_worker().cp


def list_nodes(limit: int = 1000) -> List[Dict[str, Any]]:
    out = []
    for info in _cp().list_nodes()[:limit]:
        out.append({
            "node_id": info["node_id"].hex(),
            "state": info["state"],
            "ip": info.get("ip"),
            "resources_total": info.get("resources_total", {}),
            "resources_available": info.get("resources_available", {}),
            "labels": info.get("labels", {}),
            "load": info.get("load", {}),
            "death_reason": info.get("death_reason", ""),
        })
    return out


def list_actors(limit: int = 1000,
                filters: Optional[List] = None) -> List[Dict[str, Any]]:
    out = []
    for info in _cp().list_actors()[:limit]:
        row = {
            "actor_id": info["actor_id"].hex(),
            "class_name": info.get("class_name"),
            "state": info.get("state"),
            "name": info.get("name"),
            "pid": info.get("pid"),
            "node_id": (info.get("node_id").hex()
                        if info.get("node_id") else None),
            "num_restarts": info.get("num_restarts", 0),
        }
        out.append(row)
    if filters:
        for key, op, value in filters:
            assert op == "=", "only equality filters supported"
            out = [r for r in out if str(r.get(key)) == str(value)]
    return out


def list_tasks(limit: int = 10000) -> List[Dict[str, Any]]:
    events = _cp().list_task_events(limit=limit)
    latest: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        tid = ev.get("task_id")
        cur = latest.setdefault(tid, {"task_id": tid})
        cur["state"] = ev.get("state")
        if ev.get("name"):
            cur["name"] = ev["name"]
        if ev.get("node"):
            cur["node_id"] = ev["node"]
        cur.setdefault("events", []).append(
            {"state": ev.get("state"), "time": ev.get("time")})
    return list(latest.values())[:limit]


def list_objects(limit: int = 10000) -> List[Dict[str, Any]]:
    return _cp().list_objects()[:limit]


def list_placement_groups(limit: int = 1000) -> List[Dict[str, Any]]:
    out = []
    for info in _cp().list_placement_groups()[:limit]:
        out.append({
            "placement_group_id": info["pg_id"].hex(),
            "name": info.get("name", ""),
            "state": info.get("state"),
            "strategy": info.get("strategy"),
            "bundles": info.get("bundles", []),
        })
    return out


def summarize_tasks() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for task in list_tasks():
        counts[task.get("state", "?")] = counts.get(
            task.get("state", "?"), 0) + 1
    return counts


def summarize_actors() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for actor in list_actors():
        counts[actor.get("state", "?")] = counts.get(
            actor.get("state", "?"), 0) + 1
    return counts


def summarize_objects() -> Dict[str, Any]:
    return _cp().objects_summary()
