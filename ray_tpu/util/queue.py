"""Distributed Queue (parity: ``python/ray/util/queue.py``) — an
async-actor-backed FIFO usable from any worker/driver."""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self.queue: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item, timeout: Optional[float] = None):
        try:
            await asyncio.wait_for(self.queue.put(item), timeout)
        except asyncio.TimeoutError:
            raise Full("queue is full") from None
        return True

    async def get(self, timeout: Optional[float] = None):
        try:
            return await asyncio.wait_for(self.queue.get(), timeout)
        except asyncio.TimeoutError:
            raise Empty("queue is empty") from None

    async def put_nowait(self, item):
        if self.queue.full():
            raise Full("queue is full")
        self.queue.put_nowait(item)
        return True

    async def get_nowait(self):
        if self.queue.empty():
            raise Empty("queue is empty")
        return self.queue.get_nowait()

    async def size(self) -> int:
        return self.queue.qsize()

    async def empty(self) -> bool:
        return self.queue.empty()

    async def full(self) -> bool:
        return self.queue.full()


class Queue:
    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        self.actor = _QueueActor.options(
            **(actor_options or {})).remote(maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if block:
            ray_tpu.get(self.actor.put.remote(item, timeout),
                        timeout=(timeout or 300) + 30)
        else:
            ray_tpu.get(self.actor.put_nowait.remote(item), timeout=60)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if block:
            return ray_tpu.get(self.actor.get.remote(timeout),
                               timeout=(timeout or 300) + 30)
        return ray_tpu.get(self.actor.get_nowait.remote(), timeout=60)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.size.remote(), timeout=60)

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote(), timeout=60)

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote(), timeout=60)

    def put_async(self, item: Any):
        return self.actor.put.remote(item, None)

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
