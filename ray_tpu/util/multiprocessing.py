"""``ray_tpu.util.multiprocessing`` — drop-in multiprocessing.Pool.

Parity: ``python/ray/util/multiprocessing/pool.py``: the stdlib Pool
surface (map/imap/imap_unordered/starmap/apply, async variants) backed
by cluster tasks, so ``Pool(8).map(f, xs)`` fans out across nodes
instead of local forks.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


@ray_tpu.remote
def _apply_chunk(fn, chunk, star):
    if star:
        return [fn(*item) for item in chunk]
    return [fn(item) for item in chunk]


@ray_tpu.remote
def _apply_single(fn, args, kwds):
    return fn(*args, **(kwds or {}))


class AsyncResult:
    def __init__(self, refs, chunked: bool = True, single: bool = False):
        self._refs = refs
        self._chunked = chunked
        self._single = single

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        if self._single:
            return out[0]
        if self._chunked:
            return list(itertools.chain.from_iterable(out))
        return out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs,
                                num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:  # noqa: BLE001
            return False


class Pool:
    """Task-backed process pool (``processes`` bounds concurrency only
    through cluster CPU resources; chunking mirrors stdlib)."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if initializer is not None:
            # no persistent pool processes: initializers belong in the
            # function or an ActorPool
            raise NotImplementedError(
                "Pool(initializer=...) is not supported; use "
                "ray_tpu.util.ActorPool for stateful workers")
        self._processes = processes or 8
        self._closed = False

    # -- helpers -------------------------------------------------------
    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    def _submit(self, fn, iterable, chunksize, star) -> AsyncResult:
        if self._closed:
            raise ValueError("Pool not running")
        refs = [_apply_chunk.remote(fn, chunk, star)
                for chunk in self._chunks(iterable, chunksize)]
        return AsyncResult(refs)

    # -- stdlib surface ------------------------------------------------
    def map(self, fn, iterable, chunksize=None) -> List[Any]:
        return self._submit(fn, iterable, chunksize, False).get()

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return self._submit(fn, iterable, chunksize, False)

    def starmap(self, fn, iterable, chunksize=None) -> List[Any]:
        return self._submit(fn, iterable, chunksize, True).get()

    def starmap_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return self._submit(fn, iterable, chunksize, True)

    def apply(self, fn, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args=(), kwds=None) -> AsyncResult:
        if self._closed:
            raise ValueError("Pool not running")
        return AsyncResult([_apply_single.remote(fn, args, kwds)],
                           chunked=False, single=True)

    def imap(self, fn, iterable, chunksize=1):
        if self._closed:
            raise ValueError("Pool not running")
        refs = [_apply_chunk.remote(fn, chunk, False)
                for chunk in self._chunks(iterable, chunksize)]
        for ref in refs:  # submission order
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn, iterable, chunksize=1):
        if self._closed:
            raise ValueError("Pool not running")
        refs = [_apply_chunk.remote(fn, chunk, False)
                for chunk in self._chunks(iterable, chunksize)]
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for ref in ready:
                yield from ray_tpu.get(ref)

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
