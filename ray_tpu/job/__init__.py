"""Job submission (parity: ``python/ray/dashboard/modules/job/``).

``JobSubmissionClient.submit_job(entrypoint=...)`` runs a shell
entrypoint on the cluster under a detached supervisor actor
(reference ``job_manager.py:525`` JobSupervisor): the subprocess gets
the job's ``runtime_env`` (env_vars / working_dir), its output is
captured to a per-job log, and lifecycle state
(PENDING → RUNNING → SUCCEEDED/FAILED/STOPPED) lives in the
control-plane KV so any client can query it.

Entrypoints inherit ``RAY_TPU_ADDRESS``, so a script calling
``ray_tpu.init()`` attaches to the submitting cluster as a driver
(``AttachedNode``) — tasks/actors it creates run on the cluster, like
the reference's RAY_ADDRESS injection.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import ray_tpu

_NS = "_jobs"

VALID_STATUSES = ("PENDING", "RUNNING", "SUCCEEDED", "FAILED", "STOPPED")


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str
    message: str = ""
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    metadata: Optional[Dict[str, str]] = None
    runtime_env: Optional[Dict[str, Any]] = None
    exit_code: Optional[int] = None


def _cp():
    from ray_tpu._private.worker import global_worker
    return global_worker().cp


def _put_info(info: JobInfo) -> None:
    _cp().kv_put(info.submission_id.encode(),
                 json.dumps(info.__dict__).encode(), namespace=_NS)


def _get_info(submission_id: str) -> Optional[JobInfo]:
    raw = _cp().kv_get(submission_id.encode(), namespace=_NS)
    if raw is None:
        return None
    return JobInfo(**json.loads(raw.decode()))


@ray_tpu.remote(num_cpus=0)
class _JobSupervisor:
    """Runs one job's entrypoint subprocess; owns its lifecycle."""

    def __init__(self, submission_id: str, entrypoint: str,
                 runtime_env: Optional[Dict[str, Any]],
                 metadata: Optional[Dict[str, str]]):
        import subprocess
        import threading

        from ray_tpu._private import runtime_env as _renv
        from ray_tpu._private.worker import global_worker
        self.submission_id = submission_id
        self._proc = None
        existing = _get_info(submission_id)
        if existing is not None and existing.status == "FAILED":
            # the client gave up on this submission (tombstone): a
            # late-starting supervisor must not resurrect the job
            raise RuntimeError("job submission was aborted")
        session_dir = global_worker().session_dir if hasattr(
            global_worker(), "session_dir") else os.environ.get(
            "RAY_TPU_SESSION_DIR", "/tmp")
        self.log_path = os.path.join(session_dir, "logs",
                                     f"job-{submission_id}.log")
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        env = dict(os.environ)
        cwd = None
        renv = _renv.validate(runtime_env)
        for k, v in (renv.get("env_vars") or {}).items():
            env[k] = str(v)
        if renv.get("working_dir"):
            cwd = renv["working_dir"]
        # entrypoints that call ray_tpu.init() attach to THIS cluster
        # instead of starting their own (parity: RAY_ADDRESS injection;
        # supervisors are workers, so the CP address is in their env)
        cp_addr = os.environ.get("RAY_TPU_CP_SOCK", "")
        if cp_addr:
            env.setdefault("RAY_TPU_ADDRESS", cp_addr)
        info = JobInfo(submission_id=submission_id, entrypoint=entrypoint,
                       status="RUNNING", start_time=time.time(),
                       metadata=metadata, runtime_env=runtime_env)
        _put_info(info)
        log_f = open(self.log_path, "ab")
        self._proc = subprocess.Popen(
            entrypoint, shell=True, env=env, cwd=cwd, stdout=log_f,
            stderr=subprocess.STDOUT)
        log_f.close()
        self._info = info
        self._waiter = threading.Thread(target=self._wait, daemon=True)
        self._waiter.start()

    def _wait(self) -> None:
        rc = self._proc.wait()
        self._info.end_time = time.time()
        self._info.exit_code = rc
        if self._info.status != "STOPPED":
            self._info.status = "SUCCEEDED" if rc == 0 else "FAILED"
            if rc != 0:
                self._info.message = f"entrypoint exited with code {rc}"
        _put_info(self._info)

    def stop(self) -> bool:
        if self._proc.poll() is None:
            self._info.status = "STOPPED"
            self._info.message = "stopped by user"
            _put_info(self._info)
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except Exception:  # noqa: BLE001
                self._proc.kill()
            return True
        return False

    def logs(self) -> str:
        try:
            with open(self.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def ping(self) -> str:
        return self.submission_id


class JobSubmissionClient:
    """Parity surface of ``ray.job_submission.JobSubmissionClient``."""

    def __init__(self, address: Optional[str] = None):
        # address accepted for API parity; the client talks to the
        # in-process runtime
        if not ray_tpu.is_initialized():
            raise RuntimeError("ray_tpu.init() first")

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        submission_id = submission_id or \
            f"raysubmit_{uuid.uuid4().hex[:12]}"
        if _get_info(submission_id) is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        # validate the runtime_env before anything is recorded — a bad
        # env must fail the submit call, not strand a PENDING record
        from ray_tpu._private import runtime_env as _renv
        _renv.validate(runtime_env)
        _put_info(JobInfo(submission_id=submission_id,
                          entrypoint=entrypoint, status="PENDING",
                          metadata=metadata, runtime_env=runtime_env))
        try:
            supervisor = _JobSupervisor.options(
                name=f"__job_{submission_id}",
                lifetime="detached").remote(submission_id, entrypoint,
                                            runtime_env, metadata)
            ray_tpu.get(supervisor.ping.remote(), timeout=60)
        except BaseException as e:
            _put_info(JobInfo(submission_id=submission_id,
                              entrypoint=entrypoint, status="FAILED",
                              message=f"supervisor failed: {e}",
                              end_time=time.time(), metadata=metadata,
                              runtime_env=runtime_env))
            # a slow supervisor may still come up later: kill it so it
            # can't resurrect the job behind the caller's back
            try:
                ray_tpu.kill(ray_tpu.get_actor(
                    f"__job_{submission_id}"))
            except Exception:  # noqa: BLE001
                pass
            raise
        return submission_id

    def get_job_status(self, submission_id: str) -> str:
        info = _get_info(submission_id)
        if info is None:
            raise ValueError(f"no job {submission_id!r}")
        return info.status

    def get_job_info(self, submission_id: str) -> JobInfo:
        info = _get_info(submission_id)
        if info is None:
            raise ValueError(f"no job {submission_id!r}")
        return info

    def list_jobs(self) -> List[JobInfo]:
        out = []
        for key in _cp().kv_keys(namespace=_NS):
            info = _get_info(key.decode())
            if info:
                out.append(info)
        return sorted(out, key=lambda j: j.start_time or 0)

    def get_job_logs(self, submission_id: str) -> str:
        try:
            sup = ray_tpu.get_actor(f"__job_{submission_id}")
        except ValueError:
            return ""
        return ray_tpu.get(sup.logs.remote(), timeout=30)

    def stop_job(self, submission_id: str) -> bool:
        try:
            sup = ray_tpu.get_actor(f"__job_{submission_id}")
        except ValueError:
            return False
        return ray_tpu.get(sup.stop.remote(), timeout=30)

    def delete_job(self, submission_id: str) -> bool:
        info = _get_info(submission_id)
        if info is None or info.status in ("PENDING", "RUNNING"):
            return False
        try:
            ray_tpu.kill(ray_tpu.get_actor(f"__job_{submission_id}"))
        except Exception:  # noqa: BLE001
            pass
        return _cp().kv_del(submission_id.encode(), namespace=_NS)

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(submission_id)
            if status in ("SUCCEEDED", "FAILED", "STOPPED"):
                return status
            time.sleep(0.2)
        raise TimeoutError(
            f"job {submission_id} not finished after {timeout}s")


JobStatus = VALID_STATUSES
