"""TPU accelerator manager.

Parity target: reference ``python/ray/_private/accelerators/tpu.py``
(``TPUAcceleratorManager``) — chip detection, per-task visibility via
``TPU_VISIBLE_CHIPS``, pod metadata.  Re-designed for a JAX-first stack:
detection prefers an already-imported jax, falls back to GCE/GKE metadata
env vars, and never imports jax eagerly (importing jax grabs the chips).
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

RESOURCE_NAME = "TPU"
VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
# GKE injects these; GCE metadata equivalents handled via env for now.
_TPU_CHIP_COUNT_ENVS = ("TPU_CHIP_COUNT", "TPU_NUM_DEVICES")
_TPU_TYPE_ENVS = ("TPU_ACCELERATOR_TYPE", "ACCELERATOR_TYPE")


def _jax_backend_initialized() -> bool:
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:  # noqa: BLE001
        return False


_GCE_METADATA_URL = ("http://metadata.google.internal/computeMetadata"
                     "/v1/instance/attributes/")


_gce_cache: dict = {}


def _gce_metadata(attr: str, timeout: float = 0.5) -> Optional[str]:
    """Probe the GCE metadata server for a TPU-VM attribute
    (``accelerator-type``, ``agent-worker-number``, ``instance-id`` …).
    Reference: ``python/ray/_private/accelerators/tpu.py`` queries the
    same endpoints.  Short timeout + total failure tolerance: most
    deployments (tests, GKE with env injection, bare metal) have no
    metadata server."""
    if os.environ.get("RAY_TPU_DISABLE_GCE_METADATA") == "1":
        return None
    if attr in _gce_cache:          # negatives cached too: a host with
        return _gce_cache[attr]     # no metadata server never re-probes
    _gce_cache[attr] = None
    try:
        import urllib.request
        req = urllib.request.Request(
            _GCE_METADATA_URL + attr,
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            _gce_cache[attr] = resp.read().decode().strip()
    except Exception:  # noqa: BLE001 - no metadata server here
        pass
    return _gce_cache[attr]


class TPUAcceleratorManager:
    @staticmethod
    def get_resource_name() -> str:
        return RESOURCE_NAME

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return VISIBLE_CHIPS_ENV

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        # 1. explicit override
        for env in _TPU_CHIP_COUNT_ENVS:
            value = os.environ.get(env)
            if value:
                try:
                    return int(value)
                except ValueError:
                    pass
        # 2. restricted visibility
        visible = os.environ.get(VISIBLE_CHIPS_ENV)
        if visible:
            return len([c for c in visible.split(",") if c != ""])
        # 3. jax — but only if this process ALREADY initialized the
        #    backend.  jax.devices() would otherwise claim the chips for
        #    this process, starving workers that need them.
        jax = sys.modules.get("jax")
        if jax is not None and _jax_backend_initialized():
            try:
                return len([d for d in jax.devices()
                            if d.platform not in ("cpu", "gpu")])
            except Exception:  # noqa: BLE001
                return 0
        return 0

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        for env in _TPU_TYPE_ENVS:
            value = os.environ.get(env)
            if value:
                return value
        jax = sys.modules.get("jax")
        if jax is not None and _jax_backend_initialized():
            try:
                devs = [d for d in jax.devices()
                        if d.platform not in ("cpu", "gpu")]
                if devs:
                    return getattr(devs[0], "device_kind", "TPU")
            except Exception:  # noqa: BLE001
                pass
        return _gce_metadata("accelerator-type")

    @staticmethod
    def get_current_pod_name() -> Optional[str]:
        """Name of the TPU pod slice this host belongs to (env first,
        then GCE metadata).  Surfaced as a ``TPU-{pod_name}`` node
        resource so gang tasks can target one slice."""
        name = (os.environ.get("TPU_NAME")
                or os.environ.get("TPU_POD_NAME"))
        return name or _gce_metadata("instance-id")

    @staticmethod
    def get_pod_worker_id() -> int:
        value = os.environ.get("TPU_WORKER_ID")
        if value:
            try:
                return int(value)
            except ValueError:
                pass
        meta = _gce_metadata("agent-worker-number")
        try:
            return int(meta) if meta else 0
        except ValueError:
            return 0

    @staticmethod
    def get_pod_slice_resources() -> dict:
        """Extra node resources advertising pod membership:
        ``TPU-{pod_name}`` on every slice host (reference:
        ``ray.util.accelerators.tpu`` pod resources)."""
        out = {}
        pod = TPUAcceleratorManager.get_current_pod_name()
        if pod:
            out[f"TPU-{pod}"] = 1.0
        return out

    @staticmethod
    def set_visible_accelerator_ids(ids: List[int]) -> None:
        os.environ[VISIBLE_CHIPS_ENV] = ",".join(str(i) for i in ids)

    @staticmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[int]]:
        visible = os.environ.get(VISIBLE_CHIPS_ENV)
        if visible is None:
            return None
        if visible == "":
            return []
        return [int(c) for c in visible.split(",")]

    @staticmethod
    def get_pod_worker_count() -> int:
        value = os.environ.get("TPU_WORKER_COUNT")
        return int(value) if value else 1

    @staticmethod
    def get_pod_head_resource_name() -> Optional[str]:
        """``TPU-<pod_type>-head`` resource on worker 0 of a pod slice.

        Mirrors the reference's pod-slice head resource so gang schedulers
        can target the host that must run the coordinator.
        """
        pod_type = TPUAcceleratorManager.get_current_node_accelerator_type()
        if pod_type and os.environ.get("TPU_WORKER_ID", "0") == "0":
            return f"TPU-{pod_type}-head"
        return None


def detect_num_tpus() -> int:
    return TPUAcceleratorManager.get_current_node_num_accelerators()
