"""TPU accelerator manager.

Parity target: reference ``python/ray/_private/accelerators/tpu.py``
(``TPUAcceleratorManager``) — chip detection, per-task visibility via
``TPU_VISIBLE_CHIPS``, pod metadata.  Re-designed for a JAX-first stack:
detection prefers an already-imported jax, falls back to GCE/GKE metadata
env vars, and never imports jax eagerly (importing jax grabs the chips).
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

RESOURCE_NAME = "TPU"
VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
# GKE injects these; GCE metadata equivalents handled via env for now.
_TPU_CHIP_COUNT_ENVS = ("TPU_CHIP_COUNT", "TPU_NUM_DEVICES")
_TPU_TYPE_ENVS = ("TPU_ACCELERATOR_TYPE", "ACCELERATOR_TYPE")


def _jax_backend_initialized() -> bool:
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:  # noqa: BLE001
        return False


class TPUAcceleratorManager:
    @staticmethod
    def get_resource_name() -> str:
        return RESOURCE_NAME

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return VISIBLE_CHIPS_ENV

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        # 1. explicit override
        for env in _TPU_CHIP_COUNT_ENVS:
            value = os.environ.get(env)
            if value:
                try:
                    return int(value)
                except ValueError:
                    pass
        # 2. restricted visibility
        visible = os.environ.get(VISIBLE_CHIPS_ENV)
        if visible:
            return len([c for c in visible.split(",") if c != ""])
        # 3. jax — but only if this process ALREADY initialized the
        #    backend.  jax.devices() would otherwise claim the chips for
        #    this process, starving workers that need them.
        jax = sys.modules.get("jax")
        if jax is not None and _jax_backend_initialized():
            try:
                return len([d for d in jax.devices()
                            if d.platform not in ("cpu", "gpu")])
            except Exception:  # noqa: BLE001
                return 0
        return 0

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        for env in _TPU_TYPE_ENVS:
            value = os.environ.get(env)
            if value:
                return value
        jax = sys.modules.get("jax")
        if jax is not None and _jax_backend_initialized():
            try:
                devs = [d for d in jax.devices()
                        if d.platform not in ("cpu", "gpu")]
                if devs:
                    return getattr(devs[0], "device_kind", "TPU")
            except Exception:  # noqa: BLE001
                pass
        return None

    @staticmethod
    def set_visible_accelerator_ids(ids: List[int]) -> None:
        os.environ[VISIBLE_CHIPS_ENV] = ",".join(str(i) for i in ids)

    @staticmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[int]]:
        visible = os.environ.get(VISIBLE_CHIPS_ENV)
        if visible is None:
            return None
        if visible == "":
            return []
        return [int(c) for c in visible.split(",")]

    @staticmethod
    def get_pod_worker_count() -> int:
        value = os.environ.get("TPU_WORKER_COUNT")
        return int(value) if value else 1

    @staticmethod
    def get_pod_head_resource_name() -> Optional[str]:
        """``TPU-<pod_type>-head`` resource on worker 0 of a pod slice.

        Mirrors the reference's pod-slice head resource so gang schedulers
        can target the host that must run the coordinator.
        """
        pod_type = TPUAcceleratorManager.get_current_node_accelerator_type()
        if pod_type and os.environ.get("TPU_WORKER_ID", "0") == "0":
            return f"TPU-{pod_type}-head"
        return None


def detect_num_tpus() -> int:
    return TPUAcceleratorManager.get_current_node_num_accelerators()
