from ray_tpu.accelerators.tpu import TPUAcceleratorManager, detect_num_tpus

__all__ = ["TPUAcceleratorManager", "detect_num_tpus"]
