"""``@ray_tpu.remote`` classes — actors.

Parity target: ``python/ray/actor.py`` (ActorClass / ActorHandle /
ActorMethod): ``Cls.remote(...)`` creates the actor,
``handle.method.remote(...)`` submits ordered method calls,
``.options(name=..., max_restarts=..., max_concurrency=..., ...)``.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private.task_spec import normalize_resources
from ray_tpu._private.worker import global_worker
from ray_tpu.remote_function import _apply_pg_resources, normalize_strategy


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, **opts) -> "ActorMethod":
        m = ActorMethod(self._handle, self._name,
                        opts.get("num_returns", self._num_returns))
        return m

    def remote(self, *args, **kwargs):
        worker = global_worker()
        return worker.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            {"num_returns": self._num_returns,
             # class-level retry policy applies to every method call
             # (ray parity: Actor.options(max_task_retries=...))
             "max_task_retries": self._handle._max_task_retries})

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._name}' cannot be called directly; "
            "use .remote().")


class ActorHandle:
    def __init__(self, actor_id: bytes, class_name: str = "Actor",
                 method_num_returns: Optional[Dict[str, int]] = None,
                 max_task_retries: int = 0):
        object.__setattr__(self, "_actor_id", actor_id)
        object.__setattr__(self, "_class_name", class_name)
        object.__setattr__(self, "_method_num_returns",
                           method_num_returns or {})
        object.__setattr__(self, "_max_task_retries", max_task_retries)

    def __getattr__(self, name: str) -> ActorMethod:
        # __ray_call__ runs an arbitrary fn against the actor instance;
        # other dunder/private names are real attribute errors.
        if name.startswith("_") and name != "__ray_call__":
            raise AttributeError(name)
        return ActorMethod(self, name,
                           self._method_num_returns.get(name, 1))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._method_num_returns,
                              self._max_task_retries))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return (isinstance(other, ActorHandle)
                and other._actor_id == self._actor_id)

    def _exit(self):
        """Graceful termination: queued calls run first (ray __ray_terminate__)."""
        return ActorMethod(self, "__ray_terminate__").remote()


class ActorClass:
    def __init__(self, cls, **default_opts):
        self._cls = cls
        self._default_opts = default_opts
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated "
            "directly; use .remote().")

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._default_opts)
        merged.update(opts)
        return ActorClass(self._cls, **merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._default_opts)

    def _remote(self, args, kwargs, opts: Dict[str, Any]) -> ActorHandle:
        worker = global_worker()
        from ray_tpu._private.config import GLOBAL_CONFIG
        resources = normalize_resources(
            opts.get("num_cpus"), opts.get("num_gpus"), opts.get("num_tpus"),
            opts.get("resources"), opts.get("memory"),
            default_cpus=0.0 if opts.get("num_cpus") is None else None)
        strategy = normalize_strategy(opts.get("scheduling_strategy"))
        resources = _apply_pg_resources(resources, strategy)
        max_restarts = opts.get("max_restarts")
        if max_restarts is None:
            max_restarts = GLOBAL_CONFIG.actor_default_max_restarts
        create_opts = {
            "resources": resources,
            "scheduling_strategy": strategy,
            "name": opts.get("name"),
            "namespace": opts.get("namespace", worker.namespace),
            "lifetime": opts.get("lifetime"),
            "max_restarts": max_restarts,
            "max_task_retries": opts.get("max_task_retries", 0),
            "max_concurrency": opts.get("max_concurrency", 1),
            "runtime_env": opts.get("runtime_env"),
        }
        num_returns = {
            n: getattr(m, "_num_returns")
            for n, m in vars(self._cls).items()
            if hasattr(m, "_num_returns")}
        create_opts["method_num_returns"] = num_returns
        actor_id = worker.create_actor(self._cls, args, kwargs, create_opts)
        return ActorHandle(actor_id, self._cls.__name__, num_returns,
                           opts.get("max_task_retries", 0))

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs)


def method(*, num_returns: int = 1):
    """``@ray_tpu.method(num_returns=N)`` decorator for actor methods."""
    def decorator(fn):
        fn._num_returns = num_returns
        return fn
    return decorator


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    worker = global_worker()
    actor_id = worker.cp.resolve_named_actor(name, namespace)
    if actor_id is None:
        raise ValueError(
            f"Failed to look up actor '{name}' in namespace '{namespace}'")
    info = worker.cp.get_actor_info(actor_id) or {}
    return ActorHandle(actor_id, info.get("class_name", "Actor"),
                       info.get("method_num_returns") or {},
                       max_task_retries=info.get("max_task_retries", 0))
