"""Tuner + controller (parity: ``python/ray/tune/tuner.py`` +
``tune/execution/tune_controller.py``).

Each trial is one actor executing the trainable with its config; the
controller polls reports, feeds the scheduler (ASHA early stopping), and
collects Results.  Trial-actor creation queues naturally on cluster
resources, giving max-concurrency-by-resources like the reference.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.train.result import Result
from ray_tpu.train.session import TrainContext
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search.sample import resolve


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    search_alg: Any = None
    seed: int = 0


@ray_tpu.remote
class _TrialActor:
    """Runs one trial's function in a thread; streams reports."""

    def __init__(self, trial_id: str):
        self.trial_id = trial_id

    def run(self, fn: Callable, config: Dict[str, Any],
            context: TrainContext, checkpoint):
        from ray_tpu.train.session import init_session
        session = init_session(context, checkpoint)

        def runner():
            try:
                import inspect
                out = fn(config)
                if isinstance(out, dict):
                    session.queue.put(("report", out, None))
            except BaseException as e:  # noqa: BLE001
                session.error = e
            finally:
                session.finished.set()
                session.queue.put(("done", None, None))

        threading.Thread(target=runner, daemon=True,
                         name=f"trial-{self.trial_id}").start()
        return True

    def next_report(self, timeout: float = 1.0):
        import queue as _q

        from ray_tpu.train.session import get_session
        session = get_session()
        if session is None:
            return ("done", None, None)
        try:
            item = session.queue.get(timeout=timeout)
        except _q.Empty:
            return None
        if item[0] == "done" and session.error is not None:
            from ray_tpu.exceptions import format_remote_traceback
            return ("error", {"message": str(session.error),
                              "traceback": format_remote_traceback(
                                  session.error)}, None)
        return item


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = "PENDING"
    actor: Any = None
    last_result: Dict[str, Any] = field(default_factory=dict)
    history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    iterations: int = 0


class ResultGrid:
    def __init__(self, results: List[Result], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self):
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        valid = [r for r in self._results
                 if r.error is None and metric in r.metrics]
        if not valid:
            raise ValueError(f"no completed trial reported {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(valid, key=key) if mode == "max" else min(valid,
                                                             key=key)

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for r in self._results:
            row = dict(r.metrics)
            row["error"] = str(r.error) if r.error else None
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 _restored_trials: Optional[List[Trial]] = None):
        if hasattr(trainable, "as_trainable"):
            trainable = trainable.as_trainable()
        from ray_tpu.tune.trainable import is_trainable_class, \
            wrap_trainable
        if is_trainable_class(trainable):
            trainable = wrap_trainable(trainable)
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        if self.run_config.name is None:
            self.run_config.name = f"tune_{uuid.uuid4().hex[:8]}"
        self._resources = getattr(trainable, "_tune_resources",
                                  {"CPU": 1.0})
        self._restored_trials = _restored_trials

    # ------------------------------------------------ experiment state ----
    def _save_experiment_state(self, storage: str,
                               trials: List[Trial]) -> None:
        """Journal the experiment for ``Tuner.restore`` (parity:
        ``tune/execution/experiment_state.py``)."""
        import json
        state = {"trials": [{
            "trial_id": t.trial_id, "config_idx": i,
            "status": t.status, "iterations": t.iterations,
            "last_result": _json_safe(t.last_result),
            "history": [_json_safe(h) for h in t.history],
            "checkpoint_path": t.checkpoint.path if t.checkpoint else None,
            "error": t.error, "config": _json_safe(t.config),
        } for i, t in enumerate(trials)]}
        tmp = os.path.join(storage, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, os.path.join(storage, "experiment_state.json"))

    def _save_tuner_blob(self, storage: str) -> None:
        import cloudpickle
        with open(os.path.join(storage, "tuner.pkl"), "wb") as f:
            cloudpickle.dump({
                "trainable": self.trainable,
                "param_space": self.param_space,
                "tune_config": self.tune_config,
                "run_config": self.run_config,
            }, f)

    @classmethod
    def restore(cls, path: str, trainable: Optional[Callable] = None,
                resume_errored: bool = False,
                restart_errored: bool = False) -> "Tuner":
        """Resume an interrupted experiment from its storage directory.

        Finished trials keep their results; unfinished ones re-run from
        their latest checkpoint.  ``resume_errored`` re-runs failed
        trials from their checkpoints; ``restart_errored`` re-runs them
        from scratch (parity: ``tune/tuner.py`` restore, reference
        ``:346``).
        """
        import json

        import cloudpickle
        with open(os.path.join(path, "tuner.pkl"), "rb") as f:
            blob = cloudpickle.load(f)
        with open(os.path.join(path, "experiment_state.json")) as f:
            state = json.load(f)
        trials = []
        for rec in state["trials"]:
            trial = Trial(trial_id=rec["trial_id"], config=rec["config"])
            trial.iterations = rec["iterations"]
            trial.last_result = rec["last_result"]
            trial.history = rec["history"]
            trial.error = rec["error"]
            if rec["checkpoint_path"] and os.path.exists(
                    rec["checkpoint_path"]):
                trial.checkpoint = Checkpoint(rec["checkpoint_path"])
            # only completed trials keep their terminal status; the rest
            # (RUNNING/PENDING at interruption) re-run from checkpoint
            status = rec["status"]
            if status == "ERROR" and (resume_errored or restart_errored):
                status = "PENDING"
                trial.error = None
                if restart_errored:
                    trial.checkpoint = None
                    trial.iterations = 0
            elif status not in ("TERMINATED", "ERROR"):
                status = "PENDING"
            trial.status = status
            trials.append(trial)
        return cls(trainable or blob["trainable"],
                   param_space=blob["param_space"],
                   tune_config=blob["tune_config"],
                   run_config=blob["run_config"],
                   _restored_trials=trials)

    # ---------------------------------------------------------- control ----
    def fit(self) -> ResultGrid:
        from ray_tpu.tune.schedulers import EXPLOIT
        scheduler = self.tune_config.scheduler or FIFOScheduler()
        if getattr(scheduler, "metric", None) is None and \
                hasattr(scheduler, "metric"):
            scheduler.metric = self.tune_config.metric
        storage = self.run_config.resolved_storage_path()
        os.makedirs(storage, exist_ok=True)
        self._save_tuner_blob(storage)
        from ray_tpu.tune import callbacks as cb_mod
        callbacks = list(self.run_config.callbacks or [])
        cb_mod.invoke(callbacks, "setup", storage)

        searcher = self.tune_config.search_alg
        total_trials = None
        if self._restored_trials is not None:
            trials = self._restored_trials
            searcher = None   # restored experiments replay fixed configs
        elif searcher is not None:
            # model-based search: trials are created on demand from
            # searcher.suggest as capacity frees up
            searcher.set_search_properties(self.tune_config.metric,
                                           self.tune_config.mode,
                                           self.param_space,
                                           self.tune_config.num_samples)
            trials = []
            total_trials = (searcher.total_suggestions()
                            if hasattr(searcher, "total_suggestions")
                            else None) or self.tune_config.num_samples
        else:
            configs = resolve(self.param_space,
                              self.tune_config.num_samples,
                              self.tune_config.seed)
            trials = [Trial(trial_id=f"trial_{i:05d}", config=cfg)
                      for i, cfg in enumerate(configs)]
        max_concurrent = (self.tune_config.max_concurrent_trials
                          or (4 if searcher is not None
                              else len(trials)))

        pending = [t for t in trials if t.status == "PENDING"]
        running: List[Trial] = []
        by_id = {t.trial_id: t for t in trials}
        reports: Dict[str, Any] = {}  # trial_id -> in-flight report ref
        last_save = [0.0]

        def save_state(throttled: bool = False):
            if throttled and time.monotonic() - last_save[0] < 1.0:
                return
            last_save[0] = time.monotonic()
            self._save_experiment_state(storage, trials)

        def launch(trial: Trial):
            opts = {"num_cpus": self._resources.get("CPU", 1.0)}
            if self._resources.get("TPU"):
                opts["num_tpus"] = self._resources["TPU"]
            trial.actor = _TrialActor.options(**opts).remote(
                trial.trial_id)
            ctx = TrainContext(experiment_name=self.run_config.name,
                               trial_name=trial.trial_id,
                               trial_id=trial.trial_id)
            # fire-and-forget: the call is buffered client-side until the
            # trial actor is scheduled (it may queue behind resources)
            trial.actor.run.remote(self.trainable, trial.config, ctx,
                                   trial.checkpoint)
            trial.status = "RUNNING"
            running.append(trial)
            if hasattr(scheduler, "on_trial_add"):
                scheduler.on_trial_add(trial.trial_id, trial.config)
            cb_mod.invoke(callbacks, "on_trial_start", trial)

        def retire(trial: Trial, status: str):
            trial.status = status
            running.remove(trial)
            if status == "ERROR":
                cb_mod.invoke(callbacks, "on_trial_error", trial,
                              RuntimeError(trial.error or "trial failed"))
            else:
                cb_mod.invoke(callbacks, "on_trial_complete", trial)
            scheduler.on_trial_complete(trial.trial_id)
            if searcher is not None:
                searcher.on_trial_complete(trial.trial_id,
                                           trial.last_result or None,
                                           error=status == "ERROR")
            reports.pop(trial.trial_id, None)
            ray_tpu.kill(trial.actor)
            save_state()

        def next_suggested() -> Optional[Trial]:
            if searcher is None or len(trials) >= total_trials:
                return None
            trial_id = f"trial_{len(trials):05d}"
            cfg = searcher.suggest(trial_id)
            if cfg is None:
                return None
            trial = Trial(trial_id=trial_id, config=cfg)
            trials.append(trial)
            by_id[trial_id] = trial
            return trial

        def actor_alive(trial: Trial) -> bool:
            # O(1) directory lookup: this runs per running trial per
            # poll round, so a full list_actors() scan would be
            # quadratic in cluster size (and truncates at 1000)
            from ray_tpu._private.worker import global_worker
            info = global_worker().cp.get_actor_info(
                trial.actor._actor_id)
            return bool(info) and info.get("state") == "ALIVE"

        while (pending or running
               or (searcher is not None and len(trials) < total_trials)):
            while pending and len(running) < max_concurrent:
                launch(pending.pop(0))
            while (searcher is not None
                   and len(running) < max_concurrent):
                trial = next_suggested()
                if trial is None:
                    break
                launch(trial)
            if searcher is not None and not running and not pending:
                break   # searcher declined to suggest with nothing live
            # one outstanding report poll per running trial, drained in
            # one wait() instead of a serial get() per trial
            for trial in running:
                if trial.trial_id not in reports and actor_alive(trial):
                    reports[trial.trial_id] = \
                        trial.actor.next_report.remote(0.2)
            if not reports:
                time.sleep(0.05)
                continue
            ref_to_id = {ref.binary(): tid
                         for tid, ref in reports.items()}
            ready, _ = ray_tpu.wait(list(reports.values()),
                                    num_returns=1, timeout=5)
            for ref in ready:
                tid = ref_to_id[ref.binary()]
                reports.pop(tid, None)
                trial = by_id[tid]
                if trial not in running:
                    continue
                try:
                    item = ray_tpu.get(ref, timeout=5)
                except Exception:  # noqa: BLE001 — actor died mid-poll
                    trial.error = "trial actor died"
                    retire(trial, "ERROR")
                    continue
                if item is None:
                    continue
                kind = item[0]
                if kind == "error":
                    trial.error = item[1]["traceback"]
                    retire(trial, "ERROR")
                elif kind == "done":
                    retire(trial, "TERMINATED")
                else:
                    metrics, checkpoint = item[1], item[2]
                    trial.iterations += 1
                    metrics.setdefault("training_iteration",
                                       trial.iterations)
                    metrics["trial_id"] = trial.trial_id
                    metrics["config"] = trial.config
                    trial.last_result = metrics
                    trial.history.append(metrics)
                    cb_mod.invoke(callbacks, "on_trial_result", trial,
                                  metrics)
                    if searcher is not None:
                        searcher.on_trial_result(trial.trial_id, metrics)
                    if checkpoint is not None:
                        trial.checkpoint = checkpoint.persist(
                            os.path.join(storage, trial.trial_id))
                        if getattr(checkpoint, "_ephemeral_source",
                                   False):
                            # class-Trainable wrapper tempdir: persisted
                            # copy is durable, drop the per-step source
                            shutil.rmtree(checkpoint.path,
                                          ignore_errors=True)
                        cb_mod.invoke(callbacks, "on_checkpoint", trial,
                                      trial.checkpoint.path)
                        save_state(throttled=True)
                    decision = scheduler.on_result(trial.trial_id,
                                                   metrics)
                    if decision == STOP:
                        retire(trial, "TERMINATED")
                    elif isinstance(decision, tuple) \
                            and decision[0] == EXPLOIT:
                        _, src_id, new_config = decision
                        src = by_id.get(src_id)
                        # exploit: clone the donor's checkpoint, explore
                        # with the mutated config, relaunch in place
                        ray_tpu.kill(trial.actor)
                        reports.pop(trial.trial_id, None)
                        running.remove(trial)
                        trial.config = new_config
                        if src is not None and src.checkpoint is not None:
                            trial.checkpoint = src.checkpoint
                        launch(trial)
                        save_state()

        self._save_experiment_state(storage, trials)
        results = []
        for trial in trials:
            err = None
            if trial.error:
                err = RuntimeError(
                    f"trial {trial.trial_id} failed:\n{trial.error}")
            results.append(Result(
                metrics=trial.last_result,
                checkpoint=trial.checkpoint,
                path=os.path.join(storage, trial.trial_id),
                error=err,
                metrics_history=trial.history))
        grid = ResultGrid(results, self.tune_config.metric,
                          self.tune_config.mode)
        cb_mod.invoke(callbacks, "on_experiment_end", grid)
        return grid


def _json_safe(obj):
    """Best-effort JSON projection of metrics/config dicts."""
    import json
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {k: _json_safe(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [_json_safe(v) for v in obj]
        return repr(obj)
