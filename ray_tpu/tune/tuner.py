"""Tuner + controller (parity: ``python/ray/tune/tuner.py`` +
``tune/execution/tune_controller.py``).

Each trial is one actor executing the trainable with its config; the
controller polls reports, feeds the scheduler (ASHA early stopping), and
collects Results.  Trial-actor creation queues naturally on cluster
resources, giving max-concurrency-by-resources like the reference.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.train.result import Result
from ray_tpu.train.session import TrainContext
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search.sample import resolve


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    search_alg: Any = None
    seed: int = 0


@ray_tpu.remote
class _TrialActor:
    """Runs one trial's function in a thread; streams reports."""

    def __init__(self, trial_id: str):
        self.trial_id = trial_id

    def run(self, fn: Callable, config: Dict[str, Any],
            context: TrainContext, checkpoint):
        from ray_tpu.train.session import init_session
        session = init_session(context, checkpoint)

        def runner():
            try:
                import inspect
                out = fn(config)
                if isinstance(out, dict):
                    session.queue.put(("report", out, None))
            except BaseException as e:  # noqa: BLE001
                session.error = e
            finally:
                session.finished.set()
                session.queue.put(("done", None, None))

        threading.Thread(target=runner, daemon=True,
                         name=f"trial-{self.trial_id}").start()
        return True

    def next_report(self, timeout: float = 1.0):
        import queue as _q

        from ray_tpu.train.session import get_session
        session = get_session()
        if session is None:
            return ("done", None, None)
        try:
            item = session.queue.get(timeout=timeout)
        except _q.Empty:
            return None
        if item[0] == "done" and session.error is not None:
            from ray_tpu.exceptions import format_remote_traceback
            return ("error", {"message": str(session.error),
                              "traceback": format_remote_traceback(
                                  session.error)}, None)
        return item


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = "PENDING"
    actor: Any = None
    last_result: Dict[str, Any] = field(default_factory=dict)
    history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    iterations: int = 0


class ResultGrid:
    def __init__(self, results: List[Result], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self):
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        valid = [r for r in self._results
                 if r.error is None and metric in r.metrics]
        if not valid:
            raise ValueError(f"no completed trial reported {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(valid, key=key) if mode == "max" else min(valid,
                                                             key=key)

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for r in self._results:
            row = dict(r.metrics)
            row["error"] = str(r.error) if r.error else None
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        if hasattr(trainable, "as_trainable"):
            trainable = trainable.as_trainable()
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        if self.run_config.name is None:
            self.run_config.name = f"tune_{uuid.uuid4().hex[:8]}"
        self._resources = getattr(trainable, "_tune_resources",
                                  {"CPU": 1.0})

    def fit(self) -> ResultGrid:
        configs = resolve(self.param_space, self.tune_config.num_samples,
                          self.tune_config.seed)
        scheduler = self.tune_config.scheduler or FIFOScheduler()
        if getattr(scheduler, "metric", None) is None and \
                hasattr(scheduler, "metric"):
            scheduler.metric = self.tune_config.metric
        storage = self.run_config.resolved_storage_path()
        os.makedirs(storage, exist_ok=True)

        trials = [Trial(trial_id=f"trial_{i:05d}", config=cfg)
                  for i, cfg in enumerate(configs)]
        max_concurrent = (self.tune_config.max_concurrent_trials
                          or len(trials))

        pending = list(trials)
        running: List[Trial] = []
        finished: List[Trial] = []

        def launch(trial: Trial):
            opts = {"num_cpus": self._resources.get("CPU", 1.0)}
            if self._resources.get("TPU"):
                opts["num_tpus"] = self._resources["TPU"]
            trial.actor = _TrialActor.options(**opts).remote(
                trial.trial_id)
            ctx = TrainContext(experiment_name=self.run_config.name,
                               trial_name=trial.trial_id,
                               trial_id=trial.trial_id)
            # fire-and-forget: the call is buffered client-side until the
            # trial actor is scheduled (it may queue behind resources)
            trial.actor.run.remote(self.trainable, trial.config, ctx,
                                   None)
            trial.status = "RUNNING"
            running.append(trial)

        def actor_alive(trial: Trial) -> bool:
            from ray_tpu._private.worker import global_worker
            info = global_worker().cp.get_actor_info(
                trial.actor._actor_id)
            return bool(info) and info.get("state") == "ALIVE"

        from ray_tpu.exceptions import GetTimeoutError

        while pending or running:
            while pending and len(running) < max_concurrent:
                launch(pending.pop(0))
            progressed = False
            for trial in list(running):
                if not actor_alive(trial):
                    continue  # still queued on resources
                try:
                    item = ray_tpu.get(
                        trial.actor.next_report.remote(0.2), timeout=60)
                except GetTimeoutError:
                    continue
                if item is None:
                    continue
                progressed = True
                kind = item[0]
                if kind == "error":
                    trial.status = "ERROR"
                    trial.error = item[1]["traceback"]
                    running.remove(trial)
                    finished.append(trial)
                    scheduler.on_trial_complete(trial.trial_id)
                    ray_tpu.kill(trial.actor)
                elif kind == "done":
                    trial.status = "TERMINATED"
                    running.remove(trial)
                    finished.append(trial)
                    scheduler.on_trial_complete(trial.trial_id)
                    ray_tpu.kill(trial.actor)
                else:
                    metrics, checkpoint = item[1], item[2]
                    trial.iterations += 1
                    metrics.setdefault("training_iteration",
                                       trial.iterations)
                    metrics["trial_id"] = trial.trial_id
                    metrics["config"] = trial.config
                    trial.last_result = metrics
                    trial.history.append(metrics)
                    if checkpoint is not None:
                        trial.checkpoint = checkpoint.persist(
                            os.path.join(storage, trial.trial_id))
                    decision = scheduler.on_result(trial.trial_id,
                                                   metrics)
                    if decision == STOP:
                        trial.status = "TERMINATED"
                        running.remove(trial)
                        finished.append(trial)
                        scheduler.on_trial_complete(trial.trial_id)
                        ray_tpu.kill(trial.actor)
            if not progressed:
                time.sleep(0.05)

        results = []
        for trial in trials:
            err = None
            if trial.error:
                err = RuntimeError(
                    f"trial {trial.trial_id} failed:\n{trial.error}")
            results.append(Result(
                metrics=trial.last_result,
                checkpoint=trial.checkpoint,
                path=os.path.join(storage, trial.trial_id),
                error=err,
                metrics_history=trial.history))
        return ResultGrid(results, self.tune_config.metric,
                          self.tune_config.mode)
