"""Class-based Trainable API (parity: ``tune/trainable/trainable.py``).

A class Trainable expresses resumable, stepwise training the function
API can't: ``setup(config)`` once, ``step()`` per iteration,
``save_checkpoint``/``load_checkpoint`` for pause/resume under
schedulers (ASHA stops, PBT/PB2 exploit-clones) and ``Tuner.restore``.

The Tuner adapts a Trainable subclass to the function protocol with
:func:`wrap_trainable`: each ``step()`` result is reported with a
checkpoint carrying the iteration counter, and a trial (re)started from
a checkpoint resumes from the saved iteration via ``load_checkpoint``.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Callable, Dict, Optional


class Trainable:
    """Subclass and implement ``step`` (and optionally the rest)."""

    def __init__(self):
        self.config: Dict[str, Any] = {}
        self.iteration = 0

    # -- lifecycle hooks (reference: trainable.py:293) ------------------
    def setup(self, config: Dict[str, Any]) -> None:
        """One-time initialization with the trial's hyperparams."""

    def step(self) -> Dict[str, Any]:
        """One training iteration; returns the metrics to report."""
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        """Persist state into ``checkpoint_dir`` (optional)."""
        return None

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        """Restore state saved by :meth:`save_checkpoint` (optional)."""

    def cleanup(self) -> None:
        """Teardown after the final step or external stop."""

    # -- conveniences ---------------------------------------------------
    @property
    def training_iteration(self) -> int:
        return self.iteration


_META = "_trainable_meta.json"


def wrap_trainable(cls) -> Callable:
    """Adapt a :class:`Trainable` subclass to the function protocol."""

    def fn(config: Dict[str, Any]):
        from ray_tpu import train
        from ray_tpu.train.checkpoint import Checkpoint

        t = cls()
        t.config = dict(config)
        t.setup(t.config)
        start = train.get_checkpoint()
        if start is not None:
            with start.as_directory() as d:
                meta_path = os.path.join(d, _META)
                if os.path.exists(meta_path):
                    with open(meta_path) as f:
                        t.iteration = json.load(f).get("iteration", 0)
                t.load_checkpoint(d)
        try:
            while True:
                result = t.step() or {}
                t.iteration += 1
                result.setdefault("training_iteration", t.iteration)
                ckpt_dir = tempfile.mkdtemp(prefix="trainable_ckpt_")
                t.save_checkpoint(ckpt_dir)
                with open(os.path.join(ckpt_dir, _META), "w") as f:
                    json.dump({"iteration": t.iteration}, f)
                ckpt = Checkpoint.from_directory(ckpt_dir)
                # report() is queued: the consumer persists a durable
                # copy, then deletes this source dir (one tempdir per
                # iteration must not accumulate for the trial's life)
                ckpt._ephemeral_source = True
                train.report(result, checkpoint=ckpt)
                if result.get("done"):
                    break
        finally:
            t.cleanup()

    fn.__name__ = getattr(cls, "__name__", "trainable")
    resources = getattr(cls, "_tune_resources", None)
    if resources is not None:
        fn._tune_resources = resources
    return fn


def is_trainable_class(obj: Any) -> bool:
    return isinstance(obj, type) and issubclass(obj, Trainable)
