"""Search-space primitives (parity: ``python/ray/tune/search/sample.py``).

``grid_search`` expands combinatorially; domains sample per trial.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


class LogUniform(Domain):
    def __init__(self, lower: float, upper: float, base: float = 10.0):
        import math
        self.lo = math.log(lower, base)
        self.hi = math.log(upper, base)
        self.base = base

    def sample(self, rng):
        return self.base ** rng.uniform(self.lo, self.hi)


class RandInt(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class QUniform(Domain):
    def __init__(self, lower: float, upper: float, q: float):
        self.lower, self.upper, self.q = lower, upper, q

    def sample(self, rng):
        value = rng.uniform(self.lower, self.upper)
        return round(value / self.q) * self.q


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class GridSearch:
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


# public constructors (ray.tune API names)
def choice(categories):
    return Categorical(categories)


def uniform(lower, upper):
    return Uniform(lower, upper)


def loguniform(lower, upper, base=10.0):
    return LogUniform(lower, upper, base)


def randint(lower, upper):
    return RandInt(lower, upper)


def quniform(lower, upper, q):
    return QUniform(lower, upper, q)


def sample_from(fn):
    return Function(fn)


def grid_search(values):
    return GridSearch(values)


def _expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cross product of all GridSearch entries; other values pass through."""
    import itertools
    grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
    if not grid_keys:
        return [dict(space)]
    combos = itertools.product(*(space[k].values for k in grid_keys))
    out = []
    for combo in combos:
        cfg = dict(space)
        for k, v in zip(grid_keys, combo):
            cfg[k] = v
        out.append(cfg)
    return out


def resolve(space: Dict[str, Any], num_samples: int,
            seed: int = 0) -> List[Dict[str, Any]]:
    """Expand a param space into concrete trial configs.

    grid entries cross-multiply; Domain entries are sampled once per
    (sample index, grid point) — reference BasicVariantGenerator shape.
    """
    rng = random.Random(seed)
    grids = _expand_grid(space)
    configs = []
    for _ in range(num_samples):
        for g in grids:
            cfg = {}
            for k, v in g.items():
                if isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            configs.append(cfg)
    return configs
