"""Native Gaussian-process Bayesian optimization.

Parity role: ``python/ray/tune/search/bayesopt/`` wraps the external
``bayesian-optimization`` package; here the GP (RBF kernel, Cholesky
solve) and the Expected-Improvement acquisition are implemented directly
on numpy so the searcher runs dependency-free.

Numeric dimensions are unit-mapped ([0,1]; log-scaled for loguniform);
categoricals are handled by conditioning: EI is maximized per category
combination drawn randomly (categoricals rarely dominate HPO spaces).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.tune.search.sample import Categorical, Domain
from ray_tpu.tune.search.searcher import (Searcher, numeric_dims,
                                          sample_config, to_unit,
                                          from_unit)


def _rbf(a: np.ndarray, b: np.ndarray, ls: float) -> np.ndarray:
    d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
    return np.exp(-0.5 * d2 / (ls * ls))


class GPSearcher(Searcher):
    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 n_initial_points: int = 6, n_candidates: int = 512,
                 length_scale: float = 0.25, noise: float = 1e-4,
                 xi: float = 0.01, seed: int = 0):
        super().__init__(metric, mode)
        self.n_initial = n_initial_points
        self.n_candidates = n_candidates
        self.ls = length_scale
        self.noise = noise
        self.xi = xi
        self._rng = random.Random(seed)
        self._np_rng = np.random.default_rng(seed)
        self._X: List[List[float]] = []    # unit-mapped numeric rows
        self._y: List[float] = []
        self._cats: List[Dict[str, Any]] = []  # categorical part per row
        self._live: Dict[str, Dict[str, Any]] = {}

    # ------------------------------------------------------------------
    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._y) < self.n_initial:
            cfg = sample_config(self.space, self._rng)
        else:
            cfg = self._suggest_gp()
        self._live[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        cfg = self._live.pop(trial_id, None)
        score = self._score(result)
        if cfg is None or error or score is None:
            return
        row, cats = self._encode(cfg)
        if row is None:
            return
        self._X.append(row)
        self._y.append(score)
        self._cats.append(cats)

    # ------------------------------------------------------------------
    def _dims(self):
        return [(k, d) for k, d in numeric_dims(self.space)
                if not isinstance(d, Categorical)]

    def _cat_dims(self):
        return [(k, d) for k, d in numeric_dims(self.space)
                if isinstance(d, Categorical)]

    def _encode(self, cfg):
        row = []
        for key, dom in self._dims():
            u = to_unit(dom, cfg.get(key))
            if u is None:
                return None, None
            row.append(u)
        cats = {k: cfg.get(k) for k, _ in self._cat_dims()}
        return row, cats

    def _suggest_gp(self) -> Dict[str, Any]:
        X = np.asarray(self._X, dtype=np.float64)
        y = np.asarray(self._y, dtype=np.float64)
        y_mean, y_std = y.mean(), y.std() or 1.0
        yn = (y - y_mean) / y_std

        K = _rbf(X, X, self.ls) + self.noise * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))

        cand = self._np_rng.uniform(
            0, 1, (self.n_candidates, X.shape[1]))
        Ks = _rbf(cand, X, self.ls)                    # [C, N]
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)                   # [N, C]
        var = np.clip(1.0 - (v * v).sum(0), 1e-12, None)
        sigma = np.sqrt(var)

        best = yn.max()
        imp = mu - best - self.xi
        z = imp / sigma
        # standard-normal cdf/pdf without scipy
        cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))
        pdf = np.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)
        ei = imp * cdf + sigma * pdf

        u = cand[int(np.argmax(ei))]
        cfg: Dict[str, Any] = {
            k: v for k, v in self.space.items()
            if not isinstance(v, Domain)}
        for (key, dom), uv in zip(self._dims(), u):
            cfg[key] = from_unit(dom, float(uv))
        for key, dom in self._cat_dims():
            cfg[key] = dom.sample(self._rng)
        for key, dom in self.space.items():
            if key not in cfg and isinstance(dom, Domain):
                cfg[key] = dom.sample(self._rng)
        return cfg
