"""Native Tree-structured Parzen Estimator search.

Parity role: the reference integrates HyperOpt/Optuna for TPE
(``python/ray/tune/search/hyperopt/``, ``search/optuna/``); this is the
algorithm itself (Bergstra et al., NeurIPS 2011), dependency-free.

Model: completed trials are split at the gamma-quantile of the
objective into "good" (l) and "bad" (g) sets.  Each numeric dimension
gets a per-set Parzen window (Gaussian KDE over the observed unit-mapped
values); categoricals get Laplace-smoothed count distributions.
Candidates are drawn from l and ranked by the acquisition l(x)/g(x) —
the candidate most characteristic of good trials and least like bad
ones wins.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search.sample import Categorical, Domain
from ray_tpu.tune.search.searcher import (Searcher, numeric_dims,
                                          sample_config, to_unit,
                                          from_unit)


def _kde_logpdf(x: float, points: List[float], bw: float) -> float:
    """log of a mixture of Gaussians centered at ``points``."""
    if not points:
        return 0.0
    acc = 0.0
    inv = 1.0 / (2.0 * bw * bw)
    for p in points:
        acc += math.exp(-(x - p) * (x - p) * inv)
    acc = max(acc / (len(points) * bw * math.sqrt(2 * math.pi)), 1e-300)
    return math.log(acc)


class TPESearcher(Searcher):
    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 n_initial_points: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        super().__init__(metric, mode)
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._observed: List[Dict[str, Any]] = []   # {config, score}
        self._live: Dict[str, Dict[str, Any]] = {}  # trial_id -> config

    # ------------------------------------------------------------------
    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._observed) < self.n_initial:
            cfg = sample_config(self.space, self._rng)
        else:
            cfg = self._suggest_tpe()
        self._live[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        cfg = self._live.pop(trial_id, None)
        score = self._score(result)
        if cfg is None or error or score is None:
            return
        self._observed.append({"config": cfg, "score": score})

    # ------------------------------------------------------------------
    def _split(self):
        ranked = sorted(self._observed, key=lambda o: -o["score"])
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    def _suggest_tpe(self) -> Dict[str, Any]:
        good, bad = self._split()
        dims = numeric_dims(self.space)
        cfg: Dict[str, Any] = {
            k: v for k, v in self.space.items()
            if not isinstance(v, Domain)}

        for key, dom in dims:
            if isinstance(dom, Categorical):
                cfg[key] = self._suggest_categorical(key, dom, good, bad)
            else:
                cfg[key] = self._suggest_numeric(key, dom, good, bad)
        # any remaining Domain (Function etc.): plain sample
        for key, dom in self.space.items():
            if key not in cfg and isinstance(dom, Domain):
                cfg[key] = dom.sample(self._rng)
        return cfg

    def _suggest_numeric(self, key, dom, good, bad):
        good_pts = [u for o in good
                    if (u := to_unit(dom, o["config"].get(key))) is not None]
        bad_pts = [u for o in bad
                   if (u := to_unit(dom, o["config"].get(key))) is not None]
        if not good_pts:
            return dom.sample(self._rng)
        # Scott-style bandwidth on the unit interval, floored so early
        # iterations keep exploring
        bw = max(0.1, 1.0 / max(2, len(good_pts)) ** 0.5 * 0.5)
        best_u, best_acq = None, -math.inf
        for _ in range(self.n_candidates):
            center = self._rng.choice(good_pts)
            u = min(1.0, max(0.0, self._rng.gauss(center, bw)))
            acq = (_kde_logpdf(u, good_pts, bw)
                   - _kde_logpdf(u, bad_pts, bw))
            if acq > best_acq:
                best_u, best_acq = u, acq
        return from_unit(dom, best_u)

    def _suggest_categorical(self, key, dom, good, bad):
        cats = dom.categories

        def weights(observations):
            counts = {repr(c): 1.0 for c in cats}   # Laplace smoothing
            for o in observations:
                r = repr(o["config"].get(key))
                if r in counts:
                    counts[r] += 1.0
            total = sum(counts.values())
            return {k: v / total for k, v in counts.items()}

        wg, wb = weights(good), weights(bad)
        scored = [(wg[repr(c)] / wb[repr(c)], c) for c in cats]
        # sample proportional to the acquisition ratio
        total = sum(s for s, _ in scored)
        pick = self._rng.uniform(0, total)
        for s, c in scored:
            pick -= s
            if pick <= 0:
                return c
        return scored[-1][1]
