"""Pluggable search algorithms (parity: ``python/ray/tune/search/searcher.py``).

A :class:`Searcher` proposes trial configs one at a time and learns from
completed results; the Tuner drives it when ``TuneConfig.search_alg`` is
set.  Unlike the reference's thin wrappers over external libraries
(Optuna/HyperOpt/BayesOpt...), the model-based searchers here are
implemented natively (`tpe.py`, `bayesopt.py`) so the framework has no
extra dependencies on TPU VMs.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search.sample import (Categorical, Domain, GridSearch,
                                        LogUniform, QUniform, RandInt,
                                        Uniform, resolve)


class Searcher:
    """Suggest/observe interface.

    Lifecycle per trial: ``suggest(trial_id) -> config`` (or None when
    the searcher has nothing to propose right now), zero or more
    ``on_trial_result``, then exactly one ``on_trial_complete``.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str], mode: str,
                              space: Dict[str, Any],
                              num_samples: Optional[int] = None) -> None:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        self.space = space
        self.tuner_num_samples = num_samples

    def total_suggestions(self) -> Optional[int]:
        """How many configs this searcher will propose in total; None =
        unbounded (the Tuner stops at its own num_samples)."""
        return None

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass

    # ------------------------------------------------------------------
    def _score(self, result: Optional[Dict[str, Any]]) -> Optional[float]:
        """Normalized 'bigger is better' objective from a result dict."""
        if not result or self.metric not in result:
            return None
        value = float(result[self.metric])
        return value if self.mode == "max" else -value


class BasicVariantGenerator(Searcher):
    """Grid/random search expressed as a Searcher (the default when
    ``search_alg`` is unset — same semantics as ``sample.resolve``)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 num_samples: int = 1, seed: int = 0):
        super().__init__(metric, mode)
        self.num_samples = num_samples
        self.seed = seed
        self._configs: Optional[List[Dict[str, Any]]] = None
        self._next = 0

    def _resolve(self) -> List[Dict[str, Any]]:
        if self._configs is None:
            # TuneConfig.num_samples (passed via set_search_properties)
            # wins unless this generator was built with an explicit one
            n = self.num_samples
            if n == 1 and getattr(self, "tuner_num_samples", None):
                n = self.tuner_num_samples
            self._configs = resolve(self.space, n, self.seed)
        return self._configs

    def total_suggestions(self) -> Optional[int]:
        return len(self._resolve())

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        configs = self._resolve()
        if self._next >= len(configs):
            return None
        cfg = configs[self._next]
        self._next += 1
        return cfg


def numeric_dims(space: Dict[str, Any]) -> List[Tuple[str, Domain]]:
    """The dimensions a model-based searcher can model."""
    dims = []
    for key, dom in space.items():
        if isinstance(dom, GridSearch):
            raise ValueError(
                f"{key}: grid_search cannot be combined with a "
                "model-based searcher; use tune.choice instead")
        if isinstance(dom, (Uniform, LogUniform, QUniform, RandInt,
                            Categorical)):
            dims.append((key, dom))
    return dims


def to_unit(dom: Domain, value: Any) -> Optional[float]:
    """Map a sampled value into [0, 1] for modeling; None if unmappable."""
    import math
    if isinstance(dom, Uniform):
        span = dom.upper - dom.lower
        return (float(value) - dom.lower) / span if span else 0.5
    if isinstance(dom, QUniform):
        span = dom.upper - dom.lower
        return (float(value) - dom.lower) / span if span else 0.5
    if isinstance(dom, LogUniform):
        lv = math.log(float(value), dom.base)
        span = dom.hi - dom.lo
        return (lv - dom.lo) / span if span else 0.5
    if isinstance(dom, RandInt):
        span = dom.upper - 1 - dom.lower
        return ((float(value) - dom.lower) / span) if span > 0 else 0.5
    return None


def from_unit(dom: Domain, u: float) -> Any:
    """Inverse of :func:`to_unit` (clipped to the domain)."""
    u = min(1.0, max(0.0, u))
    if isinstance(dom, Uniform):
        return dom.lower + u * (dom.upper - dom.lower)
    if isinstance(dom, QUniform):
        value = dom.lower + u * (dom.upper - dom.lower)
        return round(value / dom.q) * dom.q
    if isinstance(dom, LogUniform):
        return dom.base ** (dom.lo + u * (dom.hi - dom.lo))
    if isinstance(dom, RandInt):
        hi = max(dom.lower, dom.upper - 1)
        return int(round(dom.lower + u * (hi - dom.lower)))
    raise TypeError(f"not a numeric domain: {dom!r}")


def sample_config(space: Dict[str, Any], rng: random.Random
                  ) -> Dict[str, Any]:
    """One random config from the space (passthrough for constants)."""
    cfg = {}
    for key, dom in space.items():
        cfg[key] = dom.sample(rng) if isinstance(dom, Domain) else dom
    return cfg
