"""``ray_tpu.tune`` — hyperparameter tuning (parity: ``ray.tune``)."""

from typing import Any, Callable, Dict, Optional

from ray_tpu.train.session import get_checkpoint, get_context
from ray_tpu.train.session import report as _train_report
from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,
                                     HyperBandScheduler,
                                     MedianStoppingRule,
                                     PB2,
                                     PopulationBasedTraining)
from ray_tpu.tune.search.bayesopt import GPSearcher
from ray_tpu.tune.search.sample import (choice, grid_search, loguniform,
                                        quniform, randint, sample_from,
                                        uniform)
from ray_tpu.tune.search.searcher import BasicVariantGenerator, Searcher
from ray_tpu.tune.search.tpe import TPESearcher
from ray_tpu.tune.trainable import Trainable
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner


def report(metrics: Dict[str, Any], *, checkpoint=None) -> None:
    """Report metrics from inside a trial (parity: ``ray.tune.report``)."""
    _train_report(metrics, checkpoint=checkpoint)


def with_resources(trainable: Callable,
                   resources: Dict[str, float]) -> Callable:
    trainable._tune_resources = dict(resources)
    return trainable


def with_parameters(trainable: Callable, **params) -> Callable:
    import functools

    @functools.wraps(trainable)
    def wrapped(config):
        return trainable(config, **params)

    if hasattr(trainable, "_tune_resources"):
        wrapped._tune_resources = trainable._tune_resources
    return wrapped


def run(trainable: Callable, *, config: Optional[Dict] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "max", scheduler=None, name: Optional[str] = None,
        storage_path: Optional[str] = None, **kwargs) -> ResultGrid:
    """Classic ``tune.run`` entrypoint built on Tuner."""
    from ray_tpu.train.config import RunConfig
    tuner = Tuner(
        trainable,
        param_space=config or {},
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples,
                               scheduler=scheduler),
        run_config=RunConfig(name=name, storage_path=storage_path))
    return tuner.fit()


__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "report", "get_context",
    "get_checkpoint", "choice", "uniform", "loguniform", "randint",
    "quniform", "sample_from", "grid_search", "with_resources",
    "with_parameters", "run", "ASHAScheduler", "FIFOScheduler",
    "HyperBandScheduler", "MedianStoppingRule",
    "PB2", "PopulationBasedTraining", "Searcher", "BasicVariantGenerator",
    "TPESearcher", "GPSearcher",
]
