"""Experiment callbacks + logger integrations.

Parity targets: ``python/ray/tune/callback.py`` (Callback interface,
invoked by the tune controller at trial lifecycle points) and
``python/ray/tune/logger/{json,csv,tensorboardx}.py`` (per-trial result
logging).  External trackers (W&B, MLflow) live in
``ray_tpu.air.integrations`` and subclass :class:`LoggerCallback` the
same way the reference's ``air/integrations/{wandb,mlflow}.py`` do.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional


class Callback:
    """Hooks invoked by the Tuner's controller loop.

    Subset of the reference interface
    (``python/ray/tune/callback.py:Callback``) that the controller
    actually drives; all methods are optional overrides.
    """

    def setup(self, storage_path: str) -> None:
        """Called once before the first trial starts."""

    def on_trial_start(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial) -> None:
        pass

    def on_trial_error(self, trial, error: BaseException) -> None:
        pass

    def on_checkpoint(self, trial, checkpoint_path: str) -> None:
        """A trial checkpoint was persisted to ``checkpoint_path``."""

    def on_experiment_end(self, results) -> None:
        pass


class LoggerCallback(Callback):
    """Base for per-trial result loggers (reference:
    ``tune/logger/logger.py:LoggerCallback``): tracks per-trial state,
    funnels every lifecycle event into ``log_trial_*``."""

    def setup(self, storage_path: str) -> None:
        self.storage_path = storage_path
        os.makedirs(storage_path, exist_ok=True)

    def _trial_dir(self, trial) -> str:
        d = os.path.join(self.storage_path, trial.trial_id)
        os.makedirs(d, exist_ok=True)
        return d

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        self.log_trial_result(trial, result)

    def on_checkpoint(self, trial, checkpoint_path: str) -> None:
        self.log_trial_save(trial, checkpoint_path)

    def log_trial_save(self, trial, checkpoint_path: str) -> None:
        """Optional: persist/upload the trial's checkpoint artifact."""

    def on_trial_complete(self, trial) -> None:
        self.log_trial_end(trial, failed=False)

    def on_trial_error(self, trial, error: BaseException) -> None:
        self.log_trial_end(trial, failed=True)

    def log_trial_result(self, trial, result: Dict[str, Any]) -> None:
        raise NotImplementedError

    def log_trial_end(self, trial, failed: bool) -> None:
        pass


def _json_safe(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


class JsonLoggerCallback(LoggerCallback):
    """One JSON line per report in ``<trial>/result.json``
    (reference: ``tune/logger/json.py``)."""

    def log_trial_result(self, trial, result: Dict[str, Any]) -> None:
        path = os.path.join(self._trial_dir(trial), "result.json")
        with open(path, "a") as f:
            f.write(json.dumps({k: _json_safe(v)
                                for k, v in result.items()}) + "\n")


class CSVLoggerCallback(LoggerCallback):
    """Rolling ``<trial>/progress.csv`` (reference: ``tune/logger/csv.py``).

    The header is fixed by the FIRST report's keys; later reports write
    the intersection (the reference does the same)."""

    def __init__(self):
        self._writers: Dict[str, csv.DictWriter] = {}
        self._files: Dict[str, Any] = {}

    def log_trial_result(self, trial, result: Dict[str, Any]) -> None:
        tid = trial.trial_id
        if tid not in self._writers:
            f = open(os.path.join(self._trial_dir(trial), "progress.csv"),
                     "w", newline="")
            w = csv.DictWriter(f, fieldnames=sorted(result.keys()))
            w.writeheader()
            self._files[tid], self._writers[tid] = f, w
        w = self._writers[tid]
        w.writerow({k: _json_safe(result.get(k)) for k in w.fieldnames})
        self._files[tid].flush()

    def log_trial_end(self, trial, failed: bool) -> None:
        f = self._files.pop(trial.trial_id, None)
        if f is not None:
            f.close()
        self._writers.pop(trial.trial_id, None)


class TBXLoggerCallback(LoggerCallback):
    """TensorBoard scalars per trial (reference:
    ``tune/logger/tensorboardx.py``).  Uses ``torch.utils.tensorboard``
    when available; raises at construction otherwise so the failure is
    visible at Tuner build time, not mid-run."""

    def __init__(self):
        try:
            from torch.utils.tensorboard import SummaryWriter
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError(
                "TBXLoggerCallback needs torch.utils.tensorboard "
                "(pip package `tensorboard`)") from e
        self._writer_cls = SummaryWriter
        self._writers: Dict[str, Any] = {}

    def log_trial_result(self, trial, result: Dict[str, Any]) -> None:
        tid = trial.trial_id
        if tid not in self._writers:
            self._writers[tid] = self._writer_cls(
                log_dir=self._trial_dir(trial))
        step = int(result.get("training_iteration", 0))
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self._writers[tid].add_scalar(k, v, global_step=step)
        self._writers[tid].flush()

    def log_trial_end(self, trial, failed: bool) -> None:
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()


DEFAULT_LOGGERS = (JsonLoggerCallback, CSVLoggerCallback)


def invoke(callbacks: Optional[List[Callback]], method: str,
           *args) -> None:
    """Best-effort fan-out: a crashing callback must not kill the
    controller loop (reference behavior: warn and continue)."""
    for cb in callbacks or []:
        try:
            getattr(cb, method)(*args)
        except Exception:  # noqa: BLE001
            import logging
            logging.getLogger(__name__).warning(
                "callback %s.%s failed", type(cb).__name__, method,
                exc_info=True)
