"""Trial schedulers (parity: ``python/ray/tune/schedulers/``).

FIFOScheduler runs everything to completion; ASHAScheduler implements
async successive halving (``async_hyperband.py``): rungs at
grace_period * reduction_factor^k, trials below the rung's top-1/rf
quantile are stopped at that rung.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str):
        pass


class ASHAScheduler:
    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: int = 4,
                 max_t: int = 100):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        # rung milestones: grace * rf^k below max_t
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        # rung -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = defaultdict(list)
        self._trial_rung: Dict[str, int] = {}

    def _norm(self, value: float) -> float:
        return value if self.mode == "max" else -value

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        metric = result.get(self.metric) if self.metric else None
        if t is None or metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for rung_idx, milestone in enumerate(self.milestones):
            if t >= milestone and \
                    self._trial_rung.get(trial_id, -1) < rung_idx:
                self._trial_rung[trial_id] = rung_idx
                values = self._rungs[milestone]
                values.append(self._norm(float(metric)))
                if len(values) >= self.rf:
                    cutoff_index = max(
                        0, int(math.ceil(len(values) / self.rf)) - 1)
                    cutoff = sorted(values, reverse=True)[cutoff_index]
                    if self._norm(float(metric)) < cutoff:
                        decision = STOP
        return decision

    def on_trial_complete(self, trial_id: str):
        self._trial_rung.pop(trial_id, None)


AsyncHyperBandScheduler = ASHAScheduler


class MedianStoppingRule:
    """Stop a trial whose running-average objective falls below the
    median of the other trials' running averages at the same step
    (parity: ``python/ray/tune/schedulers/median_stopping_rule.py``)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 4, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        # trial -> list of normalized metric values (one per report)
        self._history: Dict[str, List[float]] = defaultdict(list)

    def _norm(self, value: float) -> float:
        return value if self.mode == "max" else -value

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        metric = result.get(self.metric) if self.metric else None
        if t is None or metric is None:
            return CONTINUE
        hist = self._history[trial_id]
        hist.append(self._norm(float(metric)))
        if t < self.grace_period:
            return CONTINUE
        step = len(hist)
        other_avgs = [
            sum(h[:step]) / min(step, len(h))
            for tid, h in self._history.items()
            if tid != trial_id and h]
        if len(other_avgs) < self.min_samples:
            return CONTINUE
        # lower median: lenient on ties/even counts
        median = sorted(other_avgs)[(len(other_avgs) - 1) // 2]
        best_so_far = max(hist)
        return STOP if best_so_far < median else CONTINUE

    def on_trial_complete(self, trial_id: str):
        # completed histories stay: they keep informing the median
        pass


class HyperBandScheduler:
    """Bracketed successive halving (parity:
    ``python/ray/tune/schedulers/hyperband.py``), adapted to this
    controller's async report stream.

    Trials are assigned round-robin to brackets; bracket ``s`` starts
    its trials with budget ``r0 = max_t / eta^s`` and halves at rungs
    ``r0 * eta^k``.  A rung's cutoff activates once the rung has seen
    ``eta`` results (the async adaptation — the reference pauses trials
    at rung boundaries instead, which needs checkpoint/pause support in
    the executor).
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, eta: int = 3, num_brackets: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = eta
        # brackets from most to least aggressive: bracket i halves from
        # r0 = max_t / eta^(s_max - i) (s_max = log_eta max_t), so the
        # first bracket starts at the smallest budget
        s_max = max(1, int(math.log(max_t) / math.log(eta)))
        self.brackets: List[List[int]] = []
        for i in range(num_brackets):
            s = max(0, s_max - i)
            r = max(1, round(max_t / (eta ** s)))
            rungs = []
            while r < max_t:
                rungs.append(r)
                r *= eta
            self.brackets.append(rungs)
        self._trial_bracket: Dict[str, int] = {}
        self._next_bracket = 0
        # (bracket, milestone) -> recorded values
        self._rungs: Dict[tuple, List[float]] = defaultdict(list)
        self._trial_rung: Dict[str, int] = {}

    def _norm(self, value: float) -> float:
        return value if self.mode == "max" else -value

    def on_trial_add(self, trial_id: str, config: Dict) -> None:
        self._trial_bracket[trial_id] = self._next_bracket
        self._next_bracket = (self._next_bracket + 1) % len(self.brackets)

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        metric = result.get(self.metric) if self.metric else None
        if t is None or metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        b = self._trial_bracket.setdefault(trial_id, 0)
        rungs = self.brackets[b]
        decision = CONTINUE
        # >=: time_attr need not step by 1 (seconds, stride-k reports);
        # the rung guard ensures each rung records once per trial
        for rung_idx, milestone in enumerate(rungs):
            if t >= milestone and \
                    self._trial_rung.get(trial_id, -1) < rung_idx:
                self._trial_rung[trial_id] = rung_idx
                values = self._rungs[(b, milestone)]
                values.append(self._norm(float(metric)))
                if len(values) >= self.eta:
                    keep = max(1, len(values) // self.eta)
                    cutoff = sorted(values, reverse=True)[keep - 1]
                    if self._norm(float(metric)) < cutoff:
                        decision = STOP
        return decision

    def on_trial_complete(self, trial_id: str):
        self._trial_rung.pop(trial_id, None)
        self._trial_bracket.pop(trial_id, None)


EXPLOIT = "EXPLOIT"


class PopulationBasedTraining:
    """PBT (parity: ``python/ray/tune/schedulers/pbt.py:1``).

    Every ``perturbation_interval`` iterations a trial compares itself
    against the population: bottom-quantile trials *exploit* (clone a
    top-quantile trial's checkpoint + config) and *explore* (mutate the
    cloned hyperparams — resample with ``resample_probability``, else
    perturb by 1.2x / 0.8x, or step within a list).  The controller
    enacts the decision by relaunching the trial from the donor's
    checkpoint with the mutated config.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25, seed: int = 0):
        import random
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._scores: Dict[str, float] = {}
        self._configs: Dict[str, Dict] = {}
        self._last_perturb: Dict[str, int] = {}

    def _norm(self, value: float) -> float:
        return value if self.mode == "max" else -value

    def on_trial_add(self, trial_id: str, config: Dict) -> None:
        self._configs[trial_id] = dict(config)
        self._last_perturb.setdefault(trial_id, 0)

    def _mutate(self, config: Dict) -> Dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            old = out.get(key)
            if isinstance(spec, list):
                if self._rng.random() < self.resample_prob \
                        or old not in spec:
                    out[key] = self._rng.choice(spec)
                else:  # step to a neighbour value
                    i = spec.index(old)
                    j = i + self._rng.choice((-1, 1))
                    out[key] = spec[max(0, min(len(spec) - 1, j))]
            elif callable(spec):
                if self._rng.random() < self.resample_prob \
                        or not isinstance(old, (int, float)):
                    out[key] = spec()
                else:
                    out[key] = old * self._rng.choice((0.8, 1.2))
            elif hasattr(spec, "sample"):  # tune.uniform etc.
                if self._rng.random() < self.resample_prob \
                        or not isinstance(old, (int, float)):
                    out[key] = spec.sample(self._rng)
                else:
                    out[key] = old * self._rng.choice((0.8, 1.2))
        return out

    def on_result(self, trial_id: str, result: Dict):
        t = result.get(self.time_attr)
        metric = result.get(self.metric) if self.metric else None
        if t is None or metric is None:
            return CONTINUE
        self._scores[trial_id] = self._norm(float(metric))
        self._configs.setdefault(trial_id, {}).update(
            result.get("config") or {})
        if t - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        population = sorted(self._scores, key=self._scores.get,
                            reverse=True)
        if len(population) < 2:
            return CONTINUE
        k = max(1, int(len(population) * self.quantile))
        top, bottom = population[:k], population[-k:]
        if trial_id not in bottom or trial_id in top:
            return CONTINUE
        src = self._rng.choice(top)
        new_config = self._mutate(self._configs.get(src, {}))
        return (EXPLOIT, src, new_config)

    def on_trial_complete(self, trial_id: str):
        self._scores.pop(trial_id, None)


class PB2(PopulationBasedTraining):
    """Population-Based Bandits (parity: ``tune/schedulers/pb2.py``).

    PBT with the random explore step replaced by a GP-bandit: observed
    (time, hyperparams) -> reward-improvement transitions from the whole
    population fit a Gaussian process, and the exploited trial's new
    config maximizes UCB within ``hyperparam_bounds`` — data-efficient
    mutation for small populations (Parker-Holder et al., NeurIPS '20).
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: Optional[Dict] = None,
                 quantile_fraction: float = 0.25, seed: int = 0,
                 ucb_kappa: float = 2.0, n_candidates: int = 256):
        super().__init__(metric=metric, mode=mode, time_attr=time_attr,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = dict(hyperparam_bounds or {})
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        # transition dataset: rows [t, *hyperparams] -> reward delta
        self._transitions: List = []
        self._last_metric: Dict[str, float] = {}

    def _record_transition(self, trial_id: str, t: float,
                           metric: float) -> None:
        prev = self._last_metric.get(trial_id)
        self._last_metric[trial_id] = metric
        if prev is None:
            return
        cfg = self._configs.get(trial_id, {})
        try:
            x = [float(t)] + [float(cfg[k]) for k in self.bounds]
        except (KeyError, TypeError, ValueError):
            return
        self._transitions.append((x, metric - prev))

    def on_result(self, trial_id: str, result: Dict):
        t = result.get(self.time_attr)
        metric = result.get(self.metric) if self.metric else None
        if t is not None and metric is not None:
            self._record_transition(trial_id, float(t),
                                    self._norm(float(metric)))
        return super().on_result(trial_id, result)

    def _mutate(self, config: Dict) -> Dict:
        """GP-UCB explore (overrides PBT's random perturbation)."""
        import numpy as np
        out = dict(config)
        keys = list(self.bounds)
        if not keys:
            return out
        lows = np.array([self.bounds[k][0] for k in keys], float)
        highs = np.array([self.bounds[k][1] for k in keys], float)
        span = np.maximum(highs - lows, 1e-12)
        rng = np.random.default_rng(self._rng.randrange(2 ** 31))
        cand = rng.uniform(size=(self.n_candidates, len(keys)))
        data = self._transitions[-256:]
        if len(data) >= 4:
            X = np.array([row for row, _ in data], float)
            y = np.array([dy for _, dy in data], float)
            # normalize: time to [0,1] over observed range, hps by bounds
            t0, t1 = X[:, 0].min(), max(X[:, 0].max(), X[:, 0].min() + 1)
            Xn = np.empty_like(X)
            Xn[:, 0] = (X[:, 0] - t0) / (t1 - t0)
            Xn[:, 1:] = (X[:, 1:] - lows) / span
            ystd = y.std() or 1.0
            yn = (y - y.mean()) / ystd
            ls, noise = 0.3, 1e-3
            K = _pb2_rbf(Xn, Xn, ls) + noise * np.eye(len(Xn))
            try:
                L = np.linalg.cholesky(K)
                alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
                # candidates evaluated at the *next* time step (1.0)
                C = np.concatenate(
                    [np.ones((len(cand), 1)), cand], axis=1)
                Ks = _pb2_rbf(C, Xn, ls)
                mu = Ks @ alpha
                v = np.linalg.solve(L, Ks.T)
                var = np.maximum(1.0 - (v ** 2).sum(0), 1e-9)
                score = mu + self.kappa * np.sqrt(var)
                best = cand[int(np.argmax(score))]
            except np.linalg.LinAlgError:
                best = cand[0]
        else:
            best = cand[0]
        for i, k in enumerate(keys):
            val = lows[i] + best[i] * span[i]
            if isinstance(config.get(k), int):
                val = int(round(val))
            out[k] = val
        return out


def _pb2_rbf(a, b, ls):
    import numpy as np
    d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
    return np.exp(-0.5 * d2 / (ls * ls))
