"""Trial schedulers (parity: ``python/ray/tune/schedulers/``).

FIFOScheduler runs everything to completion; ASHAScheduler implements
async successive halving (``async_hyperband.py``): rungs at
grace_period * reduction_factor^k, trials below the rung's top-1/rf
quantile are stopped at that rung.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str):
        pass


class ASHAScheduler:
    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: int = 4,
                 max_t: int = 100):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        # rung milestones: grace * rf^k below max_t
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        # rung -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = defaultdict(list)
        self._trial_rung: Dict[str, int] = {}

    def _norm(self, value: float) -> float:
        return value if self.mode == "max" else -value

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        metric = result.get(self.metric) if self.metric else None
        if t is None or metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for rung_idx, milestone in enumerate(self.milestones):
            if t == milestone and \
                    self._trial_rung.get(trial_id, -1) < rung_idx:
                self._trial_rung[trial_id] = rung_idx
                values = self._rungs[milestone]
                values.append(self._norm(float(metric)))
                if len(values) >= self.rf:
                    cutoff_index = max(
                        0, int(math.ceil(len(values) / self.rf)) - 1)
                    cutoff = sorted(values, reverse=True)[cutoff_index]
                    if self._norm(float(metric)) < cutoff:
                        decision = STOP
        return decision

    def on_trial_complete(self, trial_id: str):
        self._trial_rung.pop(trial_id, None)


AsyncHyperBandScheduler = ASHAScheduler
