"""Dashboard — HTTP observability API.

Parity (compressed): reference ``dashboard/head.py`` + modules: REST
endpoints over control-plane state (nodes/actors/tasks/objects/cluster),
Prometheus ``/metrics``, Chrome-trace ``/api/timeline``, and a minimal
HTML index.  Runs as an aiohttp server thread in the head process.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
from typing import Optional

_STATIC_INDEX = os.path.join(os.path.dirname(__file__), "static",
                             "index.html")

# fallback when the bundled SPA is missing (e.g. a trimmed install)
_INDEX_HTML = """<!doctype html>
<title>ray_tpu dashboard</title>
<h1>ray_tpu dashboard</h1>
<ul>
<li><a href="/api/cluster">cluster</a></li>
<li><a href="/api/nodes">nodes</a></li>
<li><a href="/api/actors">actors</a></li>
<li><a href="/api/tasks">tasks</a></li>
<li><a href="/api/objects">objects</a></li>
<li><a href="/api/placement_groups">placement groups</a></li>
<li><a href="/api/timeline">timeline (chrome trace)</a></li>
<li><a href="/api/jobs">jobs</a></li>
<li><a href="/api/serve">serve apps</a></li>
<li><a href="/metrics">prometheus metrics</a></li>
</ul>"""


class Dashboard:
    def __init__(self, port: int = 8265):
        self.port = port
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    def start(self) -> int:
        started = threading.Event()

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            loop.run_until_complete(self._serve(started))
            loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="dashboard")
        self._thread.start()
        if not started.wait(10):
            raise RuntimeError("dashboard failed to start")
        return self.port

    async def _serve(self, started: threading.Event):
        from aiohttp import web

        import ray_tpu
        from ray_tpu.util import state as state_api

        def json_response(data):
            return web.json_response(data)

        async def index(request):
            # the SPA frontend (dashboard/static/index.html, parity:
            # reference dashboard/client React app)
            try:
                with open(_STATIC_INDEX) as f:
                    return web.Response(text=f.read(),
                                        content_type="text/html")
            except OSError:
                return web.Response(text=_INDEX_HTML,
                                    content_type="text/html")

        async def nodes(request):
            return json_response(state_api.list_nodes())

        async def actors(request):
            return json_response(state_api.list_actors())

        async def tasks(request):
            return json_response(state_api.list_tasks())

        async def objects(request):
            return json_response(state_api.summarize_objects())

        async def pgs(request):
            return json_response(state_api.list_placement_groups())

        async def cluster(request):
            return json_response({
                "resources_total": ray_tpu.cluster_resources(),
                "resources_available": ray_tpu.available_resources(),
                "task_summary": state_api.summarize_tasks(),
                "actor_summary": state_api.summarize_actors(),
            })

        async def timeline(request):
            from ray_tpu._private.profiling import timeline as tl
            return web.Response(text=tl(), content_type="application/json")

        async def metrics(request):
            from ray_tpu.util.metrics import prometheus_text
            return web.Response(text=prometheus_text(),
                                content_type="text/plain")

        def _list_jobs_blocking():
            from ray_tpu.job import JobSubmissionClient
            try:
                return [j.__dict__
                        for j in JobSubmissionClient().list_jobs()]
            except Exception:  # noqa: BLE001 — no jobs submitted yet
                return []

        def _serve_apps_blocking():
            import ray_tpu as rt
            try:
                controller = rt.get_actor("__serve_controller__")
                return rt.get(controller.list_applications.remote(),
                              timeout=10)
            except Exception:  # noqa: BLE001 — serve not running
                return {}

        async def jobs(request):
            # cross-process RPC: keep it off the dashboard event loop
            loop = asyncio.get_running_loop()
            return json_response(
                await loop.run_in_executor(None, _list_jobs_blocking))

        async def serve_apps(request):
            loop = asyncio.get_running_loop()
            return json_response(
                await loop.run_in_executor(None, _serve_apps_blocking))

        # ---- per-entity drill-down + logs (reference:
        # dashboard/modules/{actor,node,log}) ----
        def _nm_client(node_hex: str):
            from ray_tpu._private.protocol import RpcClient
            from ray_tpu._private.worker import global_worker
            info = global_worker().cp.get_node(bytes.fromhex(node_hex))
            if info is None:
                return None
            return RpcClient(info["sock_path"])

        def _actor_detail_blocking(actor_hex: str):
            from ray_tpu._private.worker import global_worker
            info = global_worker().cp.get_actor_info(
                bytes.fromhex(actor_hex))
            if info is None:
                return None
            out = {k: (v.hex() if isinstance(v, bytes) else v)
                   for k, v in info.items()
                   if isinstance(v, (str, int, float, bool, bytes,
                                     type(None)))}
            out["actor_id"] = actor_hex
            return out

        def _node_detail_blocking(node_hex: str):
            from ray_tpu._private.worker import global_worker
            info = global_worker().cp.get_node(bytes.fromhex(node_hex))
            if info is None:
                return None
            out = dict(info)
            out["node_id"] = node_hex
            client = _nm_client(node_hex)
            if client is not None:
                try:
                    out["debug_state"] = client.call("debug_state")
                except Exception:  # noqa: BLE001
                    pass
            return {k: v for k, v in out.items()
                    if not isinstance(v, bytes)}

        async def actor_detail(request):
            loop = asyncio.get_running_loop()
            data = await loop.run_in_executor(
                None, _actor_detail_blocking,
                request.match_info["actor_id"])
            if data is None:
                return web.json_response({"error": "not found"},
                                         status=404)
            return json_response(data)

        async def node_detail(request):
            loop = asyncio.get_running_loop()
            data = await loop.run_in_executor(
                None, _node_detail_blocking,
                request.match_info["node_id"])
            if data is None:
                return web.json_response({"error": "not found"},
                                         status=404)
            return json_response(data)

        async def logs_list(request):
            node = request.query.get("node_id")
            if not node:
                return web.json_response({"error": "node_id required"},
                                         status=400)
            loop = asyncio.get_running_loop()

            def blocking():
                client = _nm_client(node)
                return client.call("list_logs") if client else None

            data = await loop.run_in_executor(None, blocking)
            if data is None:
                return web.json_response({"error": "node not found"},
                                         status=404)
            return json_response(data)

        async def logs_tail(request):
            node = request.query.get("node_id")
            name = request.query.get("name")
            n = int(request.query.get("nbytes", 65536))
            if not node or not name:
                return web.json_response(
                    {"error": "node_id and name required"}, status=400)
            loop = asyncio.get_running_loop()

            def blocking():
                client = _nm_client(node)
                return client.call("tail_log", name, n) if client \
                    else None

            data = await loop.run_in_executor(None, blocking)
            if data is None:
                return web.json_response(
                    {"error": "node or log file not found"}, status=404)
            return web.Response(text=data.decode("utf-8", "replace"),
                                content_type="text/plain")

        app = web.Application()
        app.router.add_get("/", index)
        app.router.add_get("/api/nodes", nodes)
        app.router.add_get("/api/actors", actors)
        app.router.add_get("/api/tasks", tasks)
        app.router.add_get("/api/objects", objects)
        app.router.add_get("/api/placement_groups", pgs)
        app.router.add_get("/api/cluster", cluster)
        app.router.add_get("/api/timeline", timeline)
        app.router.add_get("/api/jobs", jobs)
        app.router.add_get("/api/serve", serve_apps)
        app.router.add_get("/api/actors/{actor_id}", actor_detail)
        app.router.add_get("/api/nodes/{node_id}", node_detail)
        app.router.add_get("/api/logs", logs_list)
        app.router.add_get("/api/logs/tail", logs_tail)
        app.router.add_get("/metrics", metrics)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", self.port)
        await site.start()
        started.set()
