"""Block-scaled int8 quantize/dequantize with deterministic and
stochastic rounding.

The format: a tensor is split into ``block``-element groups along one
axis; each group stores int8 codes in ``[-127, 127]`` plus one f32
scale (``amax / 127``).  Dequantization is ``code * scale``.  The
worst-case per-element error is ``scale / 2`` (deterministic
round-to-nearest) or ``scale`` (stochastic), i.e. a relative error of
at most ``1/254`` / ``1/127`` of the block's amax —
:func:`quant_error_bound` states this for the tests' error budgets.

Rounding modes:

- ``"nearest"`` — ``jnp.round`` (round-half-to-even).  Lowest
  per-element error; used for **weights** (KV-cache entries, the
  overlap schedule's gathered weight shards), where the same value is
  read many times and bias does not accumulate across steps.
- ``"stochastic"`` — ``floor(y + u)``, ``u ~ U[0, 1)``, so
  ``E[q] = y`` exactly.  Used for **gradients** (the quantized
  reduce-scatters): each ring hop requantizes a partial *sum*, and a
  biased rounding there compounds over ranks and steps while unbiased
  noise averages out (the EQuARX argument, arXiv:2506.17615).

Two implementations of the same math:

- :func:`quantize_block_ref` — the padded, any-axis, any-size pure-JAX
  reference;
- :func:`quantize_block` — dispatches to a lane-aligned fast path
  (plain reshape, no pad/transpose data movement) when the block axis
  is the last one and ``block`` divides it — the KV cache (block =
  head_dim) and the collectives (128-element lane blocks) both hit it —
  and falls back to the reference otherwise.  Both paths produce
  bit-identical outputs for aligned shapes (``tests/test_quant.py``).

All-zero blocks store scale 0 and dequantize to exact zeros (the
quantizer divides by a guarded scale, so no inf/nan either way).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

INT8_MAX = 127


def _quantize_blocked(xb, mode: str, key) -> jnp.ndarray:
    """[..., nb, block] f32 -> ([..., nb, block] int8, [..., nb] f32)."""
    amax = jnp.max(jnp.abs(xb), axis=-1)
    scale = amax / INT8_MAX
    safe = jnp.where(scale > 0, scale, 1.0)
    y = xb / safe[..., None]
    if mode == "stochastic":
        if key is None:
            raise ValueError("mode='stochastic' needs a PRNG key")
        u = jax.random.uniform(key, xb.shape, jnp.float32)
        q = jnp.floor(y + u)
    elif mode == "nearest":
        q = jnp.round(y)
    else:
        raise ValueError(f"unknown rounding mode {mode!r}; "
                         "expected 'nearest' or 'stochastic'")
    return (jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8),
            scale.astype(jnp.float32))


def quantize_block_ref(x, *, block: int = 128, axis: int = -1,
                       mode: str = "nearest",
                       key=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Padded reference: any axis, any size (tail block zero-padded).

    Returns ``(q int8, scales f32)``; ``q`` has ``x``'s shape, the
    scales have ``axis`` replaced by ``ceil(n / block)``."""
    axis = axis % x.ndim
    n = x.shape[axis]
    nb = -(-n // block)
    xm = jnp.moveaxis(x, axis, -1).astype(jnp.float32)
    pad = nb * block - n
    if pad:
        xm = jnp.pad(xm, [(0, 0)] * (xm.ndim - 1) + [(0, pad)])
    q, scale = _quantize_blocked(
        xm.reshape(xm.shape[:-1] + (nb, block)), mode, key)
    q = q.reshape(q.shape[:-2] + (nb * block,))[..., :n]
    return (jnp.moveaxis(q, -1, axis), jnp.moveaxis(scale, -1, axis))


def quantize_block(x, *, block: int = 128, axis: int = -1,
                   mode: str = "nearest",
                   key=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Block-scaled int8 quantization (see module docstring).

    Fast path (no pad, no transpose) when ``axis`` is the trailing one
    and ``block`` divides it; the padded reference otherwise."""
    axis = axis % x.ndim
    n = x.shape[axis]
    if axis != x.ndim - 1 or n % block:
        return quantize_block_ref(x, block=block, axis=axis, mode=mode,
                                  key=key)
    nb = n // block
    xb = x.astype(jnp.float32).reshape(x.shape[:-1] + (nb, block))
    q, scale = _quantize_blocked(xb, mode, key)
    return q.reshape(x.shape), scale


def dequantize_block(q, scales, *, block: int = 128, axis: int = -1,
                     dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`quantize_block`: ``code * scale`` in f32, cast
    to ``dtype``."""
    axis = axis % q.ndim
    n = q.shape[axis]
    qm = jnp.moveaxis(q, axis, -1).astype(jnp.float32)
    sm = jnp.moveaxis(scales, axis, -1)
    full = jnp.repeat(sm, block, axis=-1)[..., :n]
    return jnp.moveaxis((qm * full).astype(dtype), -1, axis)


def quant_error_bound(x_amax: float, *, mode: str = "nearest") -> float:
    """Worst-case per-element absolute error for a block whose amax is
    ``x_amax`` — the quantity the round-trip tests assert against."""
    step = x_amax / INT8_MAX
    return step / 2 if mode == "nearest" else step


def wire_bytes(n_elements: int, *, block: int = 128,
               scale_bytes: int = 4) -> int:
    """Bytes an int8+per-block-f32-scale payload of ``n_elements``
    occupies on the wire (or in HBM) — the accounting primitive
    ``collective_bytes_per_step`` and ``KVCache.bytes`` share."""
    nb = -(-n_elements // block)
    return n_elements + nb * scale_bytes


def stochastic_key(base: int, *salts) -> jax.Array:
    """A PRNG key for in-collective stochastic rounding, folded from
    trace-time salts (rank, hop) and optionally data-dependent ints so
    the rounding pattern varies across steps, not just across elements.

    Safe inside shard_map/jit: ``base`` is a Python int; each salt may
    be a traced int32 scalar."""
    key = jax.random.PRNGKey(base)
    for s in salts:
        key = jax.random.fold_in(key, s)
    return key


def data_salt(x) -> jax.Array:
    """An int32 scalar derived from ``x``'s values (bitcast of the f32
    sum) — folded into :func:`stochastic_key` so two steps with
    different payloads round differently even at identical (rank, hop).
    One cheap reduction; NaN-free inputs assumed (grads are)."""
    s = jnp.sum(x.astype(jnp.float32))
    return jax.lax.bitcast_convert_type(s, jnp.int32)
