"""``ray_tpu.quant`` — block-scaled int8 quantization utilities.

One shared layer for the two hottest byte streams in the system, both
of which move ``cfg.dtype`` (bf16/f32) today and halve with
block-scaled int8:

- the **int8 KV cache** (``ray_tpu.inference.kv_cache``): paged K/V
  stored as int8 with one scale per (position, head) lane vector,
  dequantized inside ``decode_attention``'s 128-lane context strips —
  roughly doubling decode-slot capacity per HBM byte;
- the **quantized overlap collectives**
  (``ray_tpu.parallel.overlap``): EQuARX-style (arXiv:2506.17615)
  quantize→transfer→dequantize weight all-gathers and
  stochastic-rounding grad reduce-scatters, halving
  ``collective_bytes_per_step`` wire totals.

Everything here is pure JAX (traces into compiled steps and into
shard_map collectives); the lane-aligned fast path and the padded
reference produce identical values for aligned shapes
(``tests/test_quant.py``).
"""

from ray_tpu.quant.block_scale import (INT8_MAX,  # noqa: F401
                                       data_salt,
                                       dequantize_block,
                                       quantize_block,
                                       quantize_block_ref,
                                       quant_error_bound,
                                       stochastic_key,
                                       wire_bytes)

__all__ = [
    "INT8_MAX", "quantize_block", "quantize_block_ref",
    "dequantize_block", "quant_error_bound", "wire_bytes",
    "stochastic_key", "data_salt",
]
