"""The meeting point: versioned weight snapshots out, trajectories back.

Two host-side structures close the train<->infer loop:

- :class:`WeightStore` — the learner publishes parameter snapshots
  under a monotonic version; with a ray_tpu session up the snapshot
  goes through the **object store** (``ray_tpu.put``) so N actor
  processes share one copy (zero-copy reads from the local arena),
  otherwise an in-process slot serves the host-sim/bench path.  Either
  way actors see ``(version, host pytree)`` and hot-swap via
  ``engine.set_params`` — recompile-free by construction.

- :class:`ReplayQueue` — the bounded trajectory path back.  Capacity
  is bounded (an unbounded queue converts a slow learner into
  unbounded staleness); the **staleness bound is hard**: a batch whose
  ``param_version`` lags the latest publication by more than
  ``max_lag`` is discarded at pop time, never trained on
  (arXiv:2011.03641's concurrency-limits argument, in versions instead
  of requests).  The ``overflow`` policy only governs full-queue puts:
  ``drop`` evicts the oldest batch (freshness wins), ``wait`` rejects
  the put so the producer backs off (no trajectory wasted).
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Deque, List, Optional, Tuple

from ray_tpu.rl.rollout import TrajectoryBatch
from ray_tpu.util import chaos


class ReplayPutTimeout(RuntimeError):
    """Typed timeout for a blocking ``wait``-policy put
    (``RAY_TPU_RL_PUT_TIMEOUT``): the queue stayed full for the whole
    budget — the learner is dead or wedged, and the rollout actor must
    get control back (report to its supervisor, resync, retry) instead
    of blocking forever on a consumer that will never pop."""

    def __init__(self, timeout_s: float):
        super().__init__(
            f"ReplayQueue put timed out after {timeout_s:.3f}s: the "
            "queue stayed full (dead/wedged learner?) — rejecting the "
            "batch back to the producer (RAY_TPU_RL_PUT_TIMEOUT)")
        self.timeout_s = timeout_s

    def __reduce__(self):
        # rebuild from the constructor arg, not the message (remote
        # rollout actors ship this across the object store)
        return (ReplayPutTimeout, (self.timeout_s,))


class WeightStore:
    """Versioned param snapshots, object-store-backed when available."""

    def __init__(self, use_object_store: Optional[bool] = None):
        if use_object_store is None:
            from ray_tpu._private.worker import is_initialized
            use_object_store = is_initialized()
        self._use_ray = use_object_store
        self._version = 0
        self._slot: Any = None          # host pytree or ObjectRef
        # materialized-pytree memo: N driver-side actors syncing to
        # one publication must not pay N object-store fetches of the
        # identical snapshot (at GPT-2 size that is ~500MB per extra
        # deserialization, on the rollout critical path)
        self._mat_version = -1
        self._mat: Any = None
        self.publish_count = 0

    @property
    def version(self) -> int:
        return self._version

    def publish(self, params, *, version: Optional[int] = None) -> int:
        """Publish a host-side snapshot; returns its version.

        ``params`` may already be an ``ObjectRef`` (the LearnerGroup
        driver hands ``get_params_ref()`` straight through — the
        snapshot never round-trips the driver).  Either way publish
        returns only once the snapshot *exists* in the object store:
        a publication isn't published until actors can fetch it, and
        the publish-latency metric must price the serialization/store
        put, not a ~µs async ref handoff.

        Fault site ``rl.publish`` fires *before* any state mutates, so
        a failed publication leaves the store serving the previous
        version — actors keep rolling out on stale-but-consistent
        weights, which is the recovery contract the supervised loop
        tests."""
        chaos.maybe_fail("rl.publish")
        from ray_tpu.object_ref import ObjectRef
        if self._use_ray:
            import ray_tpu
            if isinstance(params, ObjectRef):
                ray_tpu.wait([params], num_returns=1)
            else:
                params = ray_tpu.put(params)
        self._slot = params
        self._version = (self._version + 1 if version is None
                         else int(version))
        self.publish_count += 1
        return self._version

    def latest(self) -> Tuple[int, Any]:
        """-> (version, host pytree); raises before the first publish.
        The materialized pytree is memoized per version — repeated
        calls between publications fetch nothing."""
        if self._slot is None:
            raise RuntimeError("WeightStore.latest() before the first "
                               "publish — the learner seeds version 1")
        from ray_tpu.object_ref import ObjectRef
        params = self._slot
        if isinstance(params, ObjectRef):
            if self._mat_version == self._version:
                return self._version, self._mat
            import ray_tpu
            params = ray_tpu.get(params)
            self._mat_version, self._mat = self._version, params
        return self._version, params


class ReplayQueue:
    """Bounded trajectory queue with a hard staleness bound."""

    def __init__(self, capacity: int, *, max_lag: int = 1,
                 overflow: str = "drop",
                 put_timeout: Optional[float] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if overflow not in ("drop", "wait"):
            raise ValueError(f"unknown overflow policy {overflow!r}; "
                             "expected 'drop' or 'wait'")
        if max_lag < 0:
            raise ValueError(f"max_lag must be >= 0, got {max_lag}")
        self.capacity = capacity
        self.max_lag = max_lag
        self.overflow = overflow
        # default blocking budget for ``wait``-policy puts whose call
        # passes no explicit timeout: ``RAY_TPU_RL_PUT_TIMEOUT``.
        # Single-threaded drivers (producer and consumer on one
        # thread) MUST pin this to 0 — a timed put there waits for a
        # pop that cannot happen until it returns.
        if put_timeout is None:
            from ray_tpu.rl.config import rl_config
            put_timeout = rl_config().put_timeout
        self.put_timeout = float(put_timeout)
        self._q: Deque[TrajectoryBatch] = collections.deque()
        # one lock + condition makes the queue safe for supervised
        # loops that run actors on threads; pops notify blocked
        # ``wait``-policy puts
        self._cond = threading.Condition()
        self.drops_stale = 0
        self.drops_overflow = 0
        self.puts = 0
        self.pops = 0
        self.backpressure_rejections = 0

    def __len__(self) -> int:
        with self._cond:
            return len(self._q)

    def put(self, batch: TrajectoryBatch,
            timeout: Optional[float] = None) -> bool:
        """Enqueue; returns False when a full queue rejects the put
        under the ``wait`` policy (the producer backs off — nothing
        was dropped).  Under ``drop`` the oldest batch is evicted: the
        freshest trajectories always fit.

        ``timeout`` (seconds, ``wait`` policy only; defaults to the
        queue's ``put_timeout`` = ``RAY_TPU_RL_PUT_TIMEOUT``) turns
        the rejection into a bounded block: wait up to ``timeout``
        for a pop to free space, then raise :class:`ReplayPutTimeout`
        — a producer must never block forever on a dead learner.
        Both the immediate rejection and the timeout count as
        ``backpressure_rejections``."""
        if timeout is None:
            timeout = self.put_timeout
        with self._cond:
            if len(self._q) >= self.capacity:
                if self.overflow == "wait":
                    if timeout <= 0:
                        self.backpressure_rejections += 1
                        return False
                    if not self._cond.wait_for(
                            lambda: len(self._q) < self.capacity,
                            timeout=timeout):
                        self.backpressure_rejections += 1
                        raise ReplayPutTimeout(timeout)
                else:
                    self._q.popleft()
                    self.drops_overflow += 1
            self._q.append(batch)
            self.puts += 1
            return True

    def pop(self, current_version: int) -> Optional[TrajectoryBatch]:
        """Next batch fresh enough to train on, or None.

        Discards (and counts) every batch with ``param_version <
        current_version - max_lag`` — the hard bound: the learner
        never sees a trajectory generated more than ``max_lag``
        publications ago, under either overflow policy."""
        with self._cond:
            while self._q:
                batch = self._q.popleft()
                self._cond.notify_all()
                if batch.param_version < current_version - self.max_lag:
                    self.drops_stale += 1
                    continue
                self.pops += 1
                return batch
            return None

    def drain(self) -> List[TrajectoryBatch]:
        """Empty the queue (shutdown); returns the leftover batches so
        the caller can account for them — nothing silently vanishes."""
        with self._cond:
            out = list(self._q)
            self._q.clear()
            self._cond.notify_all()
            return out
