"""RL learners over :func:`ray_tpu.models.training.build_gpt_rl_train`.

Two hosting modes for the same jitted policy-gradient step:

- :class:`InProcessLearner` — the host-sim/bench path: one sharded
  (or single-device) TrainState advanced in-process, donation intact.
- :class:`GPTPolicyLearner` — the **LearnerGroup protocol** class
  (``init_state(key)`` / ``update(params, opt_state, batch,
  allreduce=)``), so ``rllib/core/learner_group.py`` hosts GPT policy
  learners exactly like its PPO learners: N learner actors, gradients
  ring-allreduced between ``pg_grad_fn`` and ``apply_grads_fn``,
  identical optimizer steps everywhere.  ``learner_cls=
  "ray_tpu.rl.learner.GPTPolicyLearner"`` with the pickled
  ``GPTConfig`` as the module is all the group needs.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

import numpy as np


@dataclasses.dataclass
class RLLearnerConfig:
    """LearnerGroup-side config for :class:`GPTPolicyLearner` (the
    pickle-friendly counterpart of the driver's knobs).

    ``lora=True`` hosts **adapter-only** learners (r25): params are
    the LoRA A/B tree, the frozen base is derived deterministically
    from ``base_seed`` inside every learner actor (the pickle-friendly
    stand-in for a shared checkpoint restore — all ranks freeze the
    identical base by construction), and ``publish_params`` snapshots
    shrink to adapter bytes."""
    lr: float = 1e-3
    grad_clip: float = 1.0
    baseline: str = "rloo"
    seed: int = 0
    lora: bool = False
    lora_rank: int = 8
    lora_scale: float = 1.0
    base_seed: int = 0


def _rl_optimizer(lr: float, grad_clip: float):
    import optax
    return optax.chain(optax.clip_by_global_norm(grad_clip),
                       optax.adam(lr))


def _np_batch(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    return {"tokens": np.asarray(batch["tokens"], np.int32),
            "targets": np.asarray(batch["targets"], np.int32),
            "rewards": np.asarray(batch["rewards"], np.float32)}


class InProcessLearner:
    """One learner replica advanced in-process (host-sim / bench)."""

    def __init__(self, cfg, *, mesh=None, baseline: str = "rloo",
                 lr: float = 1e-3, grad_clip: float = 1.0,
                 optimizer=None, seed: int = 0, fns=None,
                 lora=None, base_params=None):
        import jax

        from ray_tpu.models import training
        from ray_tpu.parallel.mesh import make_mesh
        if mesh is None:
            mesh = make_mesh(dp=1, devices=jax.devices()[:1])
        self.cfg = cfg
        self.mesh = mesh
        # ``fns``: a pre-built ``build_gpt_rl_train`` dict — learners
        # of one geometry then share compiled steps (supervised-loop
        # restarts, A/B drivers, tests); baseline/optimizer/mesh args
        # are baked into it, so they are ignored when it is passed
        self.fns = fns or training.build_gpt_rl_train(
            cfg, mesh, baseline=baseline,
            optimizer=optimizer or _rl_optimizer(lr, grad_clip),
            lora=lora, base_params=base_params)
        self.state = self.fns["init_fn"](jax.random.PRNGKey(seed))
        self.steps = 0

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self.state, metrics = self.fns["step_fn"](self.state,
                                                  _np_batch(batch))
        self.steps += 1
        return {k: float(v) for k, v in metrics.items()}

    def params_host(self):
        """The publication form: a host (numpy) pytree snapshot —
        what ``WeightStore.publish`` ships and ``engine.set_params``
        copies in (the device TrainState stays resident here)."""
        import jax
        return jax.tree.map(np.asarray, self.state.params)

    def publish_adapter(self, store, model_id: str, *,
                        scale: Optional[float] = None) -> int:
        """Adapter-only publication (r25): put the current A/B snapshot
        into an :class:`~ray_tpu.adapters.AdapterStore` under
        ``model_id`` and return the new version.  Bytes on the wire =
        ``adapters.adapter_nbytes`` — rank-sized, not model-sized —
        which is what lets per-tenant RL republish mid-traffic; serving
        engines pick the version up through their adapter cache without
        a single recompile (the bank is a call arg)."""
        lcfg = self.fns.get("lora")
        if lcfg is None:
            raise ValueError(
                "learner was not built with lora=...; its params are "
                "full model weights — publish those through WeightStore")
        return store.put(model_id, self.params_host(),
                         scale=lcfg.scale if scale is None else scale)

    def state_host(self):
        """The *checkpoint* form: the full host TrainState (params +
        opt state + step) — what the supervised loop persists so a
        restored learner takes the identical next optimizer step."""
        import jax
        return jax.tree.map(np.asarray, self.state)

    def load_state(self, host_state) -> None:
        """Restore from a :meth:`state_host`-shaped snapshot: leaves
        go back to the devices under this learner's shardings, so the
        restored state is donation- and parity-identical to one that
        never left (the checkpoint/restore acceptance contract)."""
        import jax
        self.state = jax.device_put(
            jax.tree.unflatten(jax.tree.structure(self.state),
                               jax.tree.leaves(host_state)),
            self.fns["state_shardings"])
        self.steps = int(np.asarray(self.state.step))


class GPTPolicyLearner:
    """LearnerGroup-hosted GPT policy-gradient learner.

    Protocol parity with ``rllib.algorithms.ppo.PPOLearner``: the
    group's ``_LearnerActor`` holds (params, opt_state) and calls
    ``update`` per trajectory-batch shard; with ``allreduce`` set
    (num_learners > 1) gradients leave jit, ride the host collective
    ring, and come back through the jitted apply — every learner takes
    the identical step.
    """

    def __init__(self, module, config: RLLearnerConfig):
        import jax

        from ray_tpu.models import gpt as gpt_mod
        from ray_tpu.models import training
        from ray_tpu.parallel.mesh import make_mesh
        self.cfg = module                     # a pickled GPTConfig
        self.config = config
        mesh = make_mesh(dp=1, devices=jax.devices()[:1])
        self.tx = _rl_optimizer(config.lr, config.grad_clip)
        lora = base = None
        if config.lora:
            from ray_tpu.adapters import LoraConfig
            lora = LoraConfig(enabled=True, rank=config.lora_rank,
                              scale=config.lora_scale)
            # every learner derives the identical frozen base from the
            # shared seed — the DDP invariant (identical steps on
            # identical state) then holds for the adapter params too
            base = gpt_mod.init_params(
                self.cfg, jax.random.PRNGKey(config.base_seed))
        self.fns = training.build_gpt_rl_train(
            self.cfg, mesh, baseline=config.baseline,
            optimizer=self.tx, lora=lora, base_params=base)
        self._steps = 0

    def init_state(self, key):
        state = self.fns["init_fn"](key)
        return state.params, state.opt_state

    def update(self, params, opt_state,
               train_batch: Dict[str, np.ndarray],
               allreduce: Optional[Callable] = None):
        batch = _np_batch(train_batch)
        (loss, metrics), grads = self.fns["pg_grad_fn"](params, batch)
        if allreduce is not None:
            grads = allreduce(grads)
        params, opt_state = self.fns["apply_grads_fn"](params,
                                                       opt_state, grads)
        self._steps += 1
        out = {k: float(v) for k, v in metrics.items()}
        out["total_loss"] = float(loss)
        out["step"] = float(self._steps)
        return params, opt_state, out


class LearnerGroupAdapter:
    """Drives a :class:`~ray_tpu.rllib.core.learner_group.LearnerGroup`
    of :class:`GPTPolicyLearner` actors behind the same ``update`` /
    ``params_host`` surface as :class:`InProcessLearner`, so
    ``run_rl_loop`` is hosting-agnostic.  ``publish_ref()`` exposes the
    group's versioned object-store snapshot (``publish_params``) so
    weight publication skips the driver round-trip.

    The baseline is applied **here, over the full batch**, and the
    hosted learners run baseline-free on the resulting advantages:
    the group shards the batch on axis 0 before the learners see it,
    so an in-learner RLOO would use per-shard leave-one-out baselines
    — a different (and at shard size 1, silently baseline-free)
    estimator than the in-process path.  Driver-side advantages keep
    the DDP-hosted gradient equal to the single-learner one for the
    identical batch."""

    def __init__(self, cfg, *, num_learners: int = 1,
                 baseline: str = "rloo", lr: float = 1e-3,
                 grad_clip: float = 1.0, seed: int = 0,
                 lora: bool = False, lora_rank: int = 8,
                 lora_scale: float = 1.0, base_seed: int = 0):
        from ray_tpu.rllib.core.learner_group import LearnerGroup
        self.baseline = baseline
        self.lora_scale = lora_scale if lora else None
        self.group = LearnerGroup(
            module=cfg,
            config=RLLearnerConfig(lr=lr, grad_clip=grad_clip,
                                   baseline="none", seed=seed,
                                   lora=bool(lora), lora_rank=lora_rank,
                                   lora_scale=lora_scale,
                                   base_seed=base_seed),
            num_learners=num_learners,
            learner_cls="ray_tpu.rl.learner.GPTPolicyLearner")
        self.steps = 0

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        from ray_tpu.models.training import rl_advantages
        batch = _np_batch(batch)
        rewards = batch["rewards"]
        batch["rewards"] = np.asarray(
            rl_advantages(rewards, self.baseline), np.float32)
        metrics = self.group.update(batch)
        # the learners saw advantages in the rewards slot, so their
        # reward_mean/max report advantage stats (~0 under rloo/mean);
        # restore the true-reward figures so both hosting modes emit
        # the same metric schema
        metrics["reward_mean"] = float(np.mean(rewards))
        metrics["reward_max"] = float(np.max(rewards))
        self.steps += 1
        return metrics

    def params_host(self):
        return self.group.get_params()

    def publish_ref(self):
        """(version, ObjectRef) from the group — the object-store
        publication path."""
        return self.group.publish_params()

    def publish_adapter(self, store, model_id: str) -> int:
        """Adapter-only publication through the group's object-store
        snapshot: ``publish_params`` hands over the rank-0 params
        ObjectRef (which in lora mode IS the adapter tree) and the
        :class:`~ray_tpu.adapters.AdapterStore` shelves it under
        ``(model_id, version)`` without a driver round-trip.  The
        group's monotonic version is pinned as the store version, so
        rollout engines and the store agree on what "latest" means."""
        if self.lora_scale is None:
            raise ValueError(
                "group was not built with lora=True; its params are "
                "full model weights — publish via publish_ref()")
        version, ref = self.group.publish_params()
        return store.put(model_id, ref, scale=self.lora_scale,
                         version=version)

    def stop(self):
        self.group.stop()
