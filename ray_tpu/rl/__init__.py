"""``ray_tpu.rl`` — Podracer-style actor/learner RL for the GPT family.

The train<->infer loop, closed (ROADMAP item 3): **actor replicas**
wrap the continuous-batching inference engine to generate rollout
trajectories (sampled completions + the sampler's own chosen-token
logprobs), **learner replicas** run the REINFORCE/RLOO policy-gradient
step derived from ``models/training.py`` (:func:`~ray_tpu.models.
training.build_gpt_rl_train`), and the two meet through the object
store: the learner publishes versioned weight snapshots
(:class:`~ray_tpu.rl.replay.WeightStore`) that actors hot-swap with a
donated-buffer, zero-recompile ``engine.set_params``, while trajectory
batches flow back through a bounded, staleness-bounded
:class:`~ray_tpu.rl.replay.ReplayQueue`.  The Sebulba split of
arXiv:2104.06272, with arXiv:2011.03641's concurrency-limits argument
applied to staleness: separate replica pools, hard version-lag bound.

Config via ``RAY_TPU_RL_*`` (:func:`rl_config`); ``run_rl_loop`` is
the driver (``bench.py --rl`` / ``scratch/r14_rl.py`` entry); the
RLlib :class:`~ray_tpu.rllib.core.learner_group.LearnerGroup` hosts
multi-learner DDP via ``learner_cls="ray_tpu.rl.learner.
GPTPolicyLearner"``.
"""

from ray_tpu.rl.config import RLConfig, rl_config  # noqa: F401
from ray_tpu.rl.learner import (GPTPolicyLearner,  # noqa: F401
                                InProcessLearner, LearnerGroupAdapter,
                                RLLearnerConfig)
from ray_tpu.rl.loop import run_rl_loop  # noqa: F401
from ray_tpu.rl.replay import (ReplayPutTimeout,  # noqa: F401
                               ReplayQueue, WeightStore)
from ray_tpu.rl.reward import (batch_rewards,  # noqa: F401
                               target_token_reward)
from ray_tpu.rl.rollout import (RolloutActor,  # noqa: F401
                                TrajectoryBatch, trajectories_to_batch)

__all__ = [
    "RLConfig", "rl_config",
    "RolloutActor", "TrajectoryBatch", "trajectories_to_batch",
    "ReplayQueue", "ReplayPutTimeout", "WeightStore",
    "InProcessLearner", "GPTPolicyLearner", "LearnerGroupAdapter",
    "RLLearnerConfig",
    "target_token_reward", "batch_rewards",
    "run_rl_loop",
]
