"""Rollout actors: the inference engine as an RL trajectory generator.

The Sebulba half of the Podracer split (arXiv:2104.06272): an actor
replica owns one :class:`~ray_tpu.inference.InferenceEngine` — the
same paged-cache, bucketed-AOT, continuous-batching engine serving
traffic — and turns (prompt, params@version) into trajectory batches:
sampled completions, the sampler's own chosen-token logprobs
(``log pi(a|s)``, parity-tested against a teacher-forced recompute),
and a programmatic reward.  Weight publications from the learner
hot-swap in through :meth:`~ray_tpu.inference.InferenceEngine.set_params`
— params are call args of the AOT executables, so a swap costs zero
recompiles and the donated-buffer semantics keep exactly one resident
snapshot per actor (both asserted in ``tests/test_rl.py``).

Actor replicas of the same geometry share one executable cache: the
N-th replica compiles nothing.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.inference import InferenceEngine, SamplingParams
from ray_tpu.rl.reward import batch_rewards


@dataclasses.dataclass
class TrajectoryBatch:
    """One rollout batch, in the learner's batch layout.

    ``tokens``/``targets`` follow :func:`ray_tpu.models.training.
    build_gpt_rl_train`'s contract: ``targets[b, t]`` is the token the
    policy *sampled* at position ``t+1`` when that position is part of
    the completion, ``-1`` everywhere else (prompt and pad positions
    carry no gradient).  ``param_version`` tags which published
    snapshot generated the batch — the staleness bound prices batches
    in these versions.
    """
    tokens: np.ndarray          # [B, S] int32
    targets: np.ndarray         # [B, S] int32  (-1 = masked)
    rewards: np.ndarray         # [B] f32
    logprobs: List[List[float]]  # actor-side per-token model logprobs
    completions: List[List[int]]
    param_version: int
    actor_id: int = 0
    gen_tokens: int = 0
    wall_s: float = 0.0

    def as_learner_batch(self) -> Dict[str, np.ndarray]:
        return {"tokens": self.tokens, "targets": self.targets,
                "rewards": self.rewards}


def trajectories_to_batch(prompts: Sequence[Sequence[int]],
                          completions: List[List[int]],
                          seq_len: int) -> Dict[str, np.ndarray]:
    """Pack (prompt, completion) pairs into fixed [B, seq_len] arrays.

    Fixed shapes are the whole point: every rollout batch compiles the
    learner step exactly once.  Pad token is 0 — masked targets make
    its value irrelevant."""
    B = len(prompts)
    tokens = np.zeros((B, seq_len), np.int32)
    targets = np.full((B, seq_len), -1, np.int32)
    for b, (prompt, comp) in enumerate(zip(prompts, completions)):
        if not prompt:
            # lo=0 would slice targets[b, -1:...] and assign nothing:
            # an all-masked row trains as a silent no-op — refuse
            raise ValueError(f"trajectory {b}: empty prompt (the "
                             "first action needs a context position)")
        seq = list(prompt) + list(comp)
        if len(seq) > seq_len:
            raise ValueError(f"trajectory {b}: prompt+completion = "
                             f"{len(seq)} tokens > seq_len {seq_len}")
        tokens[b, :len(seq)] = seq
        # position t predicts token t+1; only sampled tokens are
        # actions
        lo, hi = len(prompt), len(seq)
        targets[b, lo - 1:hi - 1] = seq[lo:hi]
    return {"tokens": tokens, "targets": targets}


class PromptDataset:
    """Deterministic prompt stream for rollout actors, built on the
    streaming data plane's document schedule.

    The r17 counterpart of the trainer's packed stream: prompts come
    from a :class:`~ray_tpu.data.DocumentSource` in the same
    round-robin shard order, truncated to a fixed ``prompt_len`` (the
    learner wants fixed ``[B, S]`` shapes — one compile), documents
    shorter than ``prompt_len`` are skipped (counted).  The position
    serializes through :meth:`cursor_array` / ``cursor=`` exactly like
    the trainer's, so a preempted RL run resumes on the identical
    prompt sequence — and a dead reader restarts with the fetch
    re-issued verbatim (exactly-once, same as training).
    """

    def __init__(self, source, *, prompt_len: int, cursor=None,
                 readers: Optional[int] = None,
                 retries: Optional[int] = None):
        from ray_tpu.data.config import data_config
        from ray_tpu.data.stream import _DocSchedule, StreamCursor
        if prompt_len < 1:
            raise ValueError(f"prompt_len must be >= 1, got "
                             f"{prompt_len}")
        dcfg = data_config()
        self.prompt_len = int(prompt_len)
        if cursor is None:
            cursor = StreamCursor(
                seed=0, num_shards=source.num_shards,
                batch_size=0, seq_len=self.prompt_len, pack=False,
                shard_offsets=[0] * source.num_shards)
        elif not isinstance(cursor, StreamCursor):
            cursor = StreamCursor.from_array(cursor)
        if (cursor.num_shards, cursor.seq_len) != \
                (source.num_shards, self.prompt_len):
            raise ValueError(
                "prompt cursor geometry mismatch: cursor has "
                f"(shards, prompt_len)=({cursor.num_shards}, "
                f"{cursor.seq_len}), dataset wants "
                f"({source.num_shards}, {self.prompt_len})")
        self._cursor = cursor.copy()
        self._schedule = _DocSchedule(
            source, self._cursor,
            readers=dcfg.readers if readers is None else readers,
            retries=dcfg.retries if retries is None else retries)
        self.skipped_short = 0

    def next_prompts(self, n: int) -> List[List[int]]:
        """The next ``n`` fixed-length prompts of the schedule.

        Documents shorter than ``prompt_len`` are skipped (counted);
        a full epoch of skips without one usable document raises
        loudly — the schedule wraps epochs forever, so a corpus with
        no long-enough document would otherwise spin here."""
        out: List[List[int]] = []
        skipped_run = 0
        total = self._schedule.source.total_docs()
        while len(out) < n:
            doc_id, toks = self._schedule.next_doc()
            if len(toks) < self.prompt_len:
                self.skipped_short += 1
                skipped_run += 1
                if skipped_run > total:
                    raise ValueError(
                        f"no document in the source reaches "
                        f"prompt_len={self.prompt_len} (skipped a "
                        f"full epoch of {total} documents) — lower "
                        "prompt_len or fix the corpus")
                continue
            skipped_run = 0
            out.append([int(t) for t in toks[:self.prompt_len]])
        return out

    @property
    def reader_restarts(self) -> int:
        return self._schedule.reader_restarts

    def cursor_array(self) -> np.ndarray:
        """Fixed-capacity serialization (checkpoint extras)."""
        return self._cursor.to_array()


class RolloutActor:
    """One rollout replica: engine + reward + version bookkeeping."""

    def __init__(self, cfg, params, *, actor_id: int = 0,
                 temperature: float = 1.0,
                 eos_token: Optional[int] = None,
                 engine_kwargs: Optional[Dict[str, Any]] = None):
        self.actor_id = actor_id
        self.temperature = float(temperature)
        self.eos_token = eos_token
        kw = dict(engine_kwargs or {})
        # rollouts have no deadline semantics: an actor engine must not
        # inherit the serving fleet's RAY_TPU_INFER_*DEADLINE defaults
        # (an expired request would truncate a trajectory mid-flight)
        kw.setdefault("ttft_deadline", 0)
        kw.setdefault("deadline", 0)
        self.engine = InferenceEngine(cfg, params, **kw)
        self._rollouts = 0

    @property
    def param_version(self) -> int:
        return self.engine.param_version

    def sync(self, version: int, params) -> None:
        """Hot-swap to a published snapshot (no-op when current)."""
        if version != self.engine.param_version:
            self.engine.set_params(params, version=version)

    def rollout(self, prompts: Sequence[Sequence[int]], *,
                horizon: int, seq_len: int,
                reward_fn: Callable[[Sequence[int]], float],
                seed: int = 0) -> TrajectoryBatch:
        """Generate one trajectory batch under the current params.

        Per-trajectory sampling seeds derive from ``(seed, row)``
        through the engine's per-sequence PRNG, so a rollout is a pure
        function of (params, prompts, seed) — co-batching, slot
        assignment and actor count never change the trajectories
        (the engine's solo-vs-batched invariant).

        Fault site ``rl.rollout`` fires on entry — before any request
        is submitted — so an injected actor death leaves the engine
        drained (nothing held) and the supervisor can replace the
        actor without leaking slots or pages."""
        from ray_tpu.util import chaos
        chaos.maybe_fail("rl.rollout")
        t0 = time.monotonic()
        rids = [self.engine.submit(
            p, max_new_tokens=horizon,
            sampling=SamplingParams(temperature=self.temperature,
                                    seed=seed + i),
            eos_token=self.eos_token)
            for i, p in enumerate(prompts)]
        toks: Dict[int, List[int]] = {r: [] for r in rids}
        lps: Dict[int, List[float]] = {r: [] for r in rids}
        while self.engine.has_work():
            for ev in self.engine.step():
                if ev.error is not None:
                    # a request died mid-rollout (deadline set despite
                    # the defaults, engine fault): the trajectory is
                    # incomplete — appending the terminal (-1, 0.0)
                    # event would train the learner on a fake action,
                    # so the actor fails loudly and the supervisor
                    # replaces it
                    raise ev.error
                rid, tok, _done = ev
                toks[rid].append(tok)
                lps[rid].append(ev.logprob)
        completions = [toks[r] for r in rids]
        logprobs = [lps[r] for r in rids]
        wall = time.monotonic() - t0
        arrays = trajectories_to_batch(prompts, completions, seq_len)
        rewards = batch_rewards(reward_fn, completions)
        self._rollouts += 1
        return TrajectoryBatch(
            tokens=arrays["tokens"], targets=arrays["targets"],
            rewards=rewards, logprobs=logprobs,
            completions=completions,
            param_version=self.engine.param_version,
            actor_id=self.actor_id,
            gen_tokens=sum(len(c) for c in completions),
            wall_s=wall)

    def idle(self) -> bool:
        """True when the engine holds no slots/pages/requests — the
        clean-shutdown invariant the loop asserts.  Every page must be
        back in the allocator's free pool (prefix-cache idle pages
        count as free — the r12 accounting), every slot free, nothing
        queued."""
        sched = self.engine.scheduler
        return (not sched.active and not sched.waiting
                and len(sched.free_slots) == self.engine.slots
                and sched.allocator.free_count
                == sched.allocator.num_pages - 1)
