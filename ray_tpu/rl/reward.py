"""Programmatic rewards for the end-to-end RL proof.

No learned reward model: rewards here are pure functions of the
sampled token ids, so the whole actor->queue->learner loop is
deterministic under fixed seeds and the "does the reward actually go
up" acceptance test has no moving parts besides the policy.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import numpy as np


def target_token_reward(target: int, *, length_penalty: float = 0.0,
                        eos_token: int = None
                        ) -> Callable[[Sequence[int]], float]:
    """Length-penalized target-token reward.

    ``reward(completion) = #(tok == target) - length_penalty * len``
    where EOS (when configured) is excluded from both counts — it ends
    the episode, it is not part of the answer.  The optimum is a
    completion dense in ``target`` that stops as soon as the penalty
    outweighs another target token; with ``length_penalty = 0`` it
    reduces to the plain occurrence count.  An easy, smooth objective:
    every extra unit of ``P(target)`` raises the expected reward, so a
    correct policy gradient must improve it monotonically in
    expectation — which is exactly what the acceptance test asserts.
    """

    def reward(completion: Sequence[int]) -> float:
        toks = [t for t in completion
                if eos_token is None or t != eos_token]
        hits = sum(1 for t in toks if t == target)
        return float(hits) - length_penalty * len(toks)

    return reward


def batch_rewards(reward_fn: Callable[[Sequence[int]], float],
                  completions: List[List[int]]) -> np.ndarray:
    """Apply a per-completion reward to a rollout batch -> [B] f32."""
    return np.array([reward_fn(c) for c in completions], np.float32)
