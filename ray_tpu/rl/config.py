"""RL-loop env knobs — the single home for actor/learner config.

Follows the ``infer_config()`` precedent exactly: one frozen dataclass
resolved from the environment once, ``refresh=True`` for tests and A/B
drivers that flip flags after import.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional


@dataclasses.dataclass(frozen=True)
class RLConfig:
    """Actor/learner RL-loop knobs, resolved once from the environment.

    - ``RAY_TPU_RL_ACTORS`` (default ``1``): rollout actor replicas.
      Each wraps its own :class:`~ray_tpu.inference.InferenceEngine`;
      replicas of the same geometry share one executable cache, so
      extra actors cost pages/slots, not compiles.
    - ``RAY_TPU_RL_BATCH`` (default ``8``): trajectories per rollout
      batch (also the RLOO batch — the leave-one-out baseline needs
      >= 2).
    - ``RAY_TPU_RL_HORIZON`` (default ``16``): max new tokens per
      rollout (trajectories ending early on EOS are padded — learner
      batch shapes stay fixed, one compile).
    - ``RAY_TPU_RL_QUEUE`` (default ``4``): trajectory-queue capacity
      (batches).  Bounded by design — an unbounded queue converts a
      slow learner into unbounded staleness.
    - ``RAY_TPU_RL_MAX_LAG`` (default ``1``): staleness bound, in
      learner param versions.  A trajectory batch generated at version
      ``v`` is dropped (never trained on) once the learner has moved
      past ``v + max_lag``; actors re-sync before every rollout, so
      their params never lag the latest publication by more than the
      publish cadence.
    - ``RAY_TPU_RL_OVERFLOW`` (default ``drop``): full-queue policy —
      ``drop`` evicts the oldest batch (freshness wins), ``wait``
      rejects the put so the producer backs off (throughput wins).
      The staleness bound above is hard either way.
    - ``RAY_TPU_RL_PUBLISH_EVERY`` (default ``1``): learner steps
      between weight publications (higher = fewer snapshots, more
      actor-side lag).
    - ``RAY_TPU_RL_BASELINE`` (default ``rloo``): advantage baseline —
      ``rloo`` (leave-one-out), ``mean`` (batch mean), ``none``
      (plain REINFORCE).
    - ``RAY_TPU_RL_TEMPERATURE`` (default ``1.0``): rollout sampling
      temperature.  ``1.0`` keeps the behavior distribution equal to
      the model softmax the learner differentiates (on-policy); other
      values are exploration knobs that reintroduce off-policy bias.
    - ``RAY_TPU_RL_PUT_TIMEOUT`` (default ``0`` = non-blocking):
      seconds a ``wait``-policy queue put may block for a pop to free
      space before raising the typed
      :class:`~ray_tpu.rl.replay.ReplayPutTimeout` — the bound that
      keeps a rollout actor from blocking forever on a dead learner
      (timeouts count as ``backpressure_rejections``).
    """
    actors: int = 1
    batch: int = 8
    horizon: int = 16
    queue: int = 4
    max_lag: int = 1
    overflow: str = "drop"
    publish_every: int = 1
    baseline: str = "rloo"
    temperature: float = 1.0
    put_timeout: float = 0.0


_CONFIG: Optional[RLConfig] = None


def rl_config(refresh: bool = False) -> RLConfig:
    """The process-wide :class:`RLConfig` (env read once, cached)."""
    global _CONFIG
    if _CONFIG is None or refresh:
        env = os.environ.get
        overflow = env("RAY_TPU_RL_OVERFLOW", "drop")
        if overflow not in ("drop", "wait"):
            print(f"RAY_TPU_RL_OVERFLOW={overflow!r} unknown; "
                  "using 'drop'", file=sys.stderr)
            overflow = "drop"
        baseline = env("RAY_TPU_RL_BASELINE", "rloo")
        if baseline not in ("rloo", "mean", "none"):
            print(f"RAY_TPU_RL_BASELINE={baseline!r} unknown; "
                  "using 'rloo'", file=sys.stderr)
            baseline = "rloo"

        def pos_int(name, default):
            val = int(env(name, str(default)))
            if val < 1:
                print(f"{name}={val} must be >= 1; using {default}",
                      file=sys.stderr)
                return default
            return val

        temperature = float(env("RAY_TPU_RL_TEMPERATURE", "1.0"))
        if temperature <= 0:
            # <= 0 means greedy sampling: every trajectory in a batch
            # is identical, all advantages are 0, every learner step a
            # no-op — degenerate silently is the one thing it must not
            # do
            print(f"RAY_TPU_RL_TEMPERATURE={temperature} must be > 0 "
                  "(greedy rollouts zero the policy gradient); "
                  "using 1.0", file=sys.stderr)
            temperature = 1.0
        put_timeout = float(env("RAY_TPU_RL_PUT_TIMEOUT", "0"))
        if put_timeout < 0:
            print(f"RAY_TPU_RL_PUT_TIMEOUT={put_timeout} negative; "
                  "using 0 (non-blocking puts)", file=sys.stderr)
            put_timeout = 0.0
        max_lag = int(env("RAY_TPU_RL_MAX_LAG", "1"))
        if max_lag < 0:
            print(f"RAY_TPU_RL_MAX_LAG={max_lag} negative; using 0 "
                  "(actors only ever train fully fresh batches)",
                  file=sys.stderr)
            max_lag = 0
        _CONFIG = RLConfig(
            actors=pos_int("RAY_TPU_RL_ACTORS", 1),
            batch=pos_int("RAY_TPU_RL_BATCH", 8),
            horizon=pos_int("RAY_TPU_RL_HORIZON", 16),
            queue=pos_int("RAY_TPU_RL_QUEUE", 4),
            max_lag=max_lag,
            overflow=overflow,
            publish_every=pos_int("RAY_TPU_RL_PUBLISH_EVERY", 1),
            baseline=baseline,
            temperature=temperature,
            put_timeout=put_timeout,
        )
    return _CONFIG
