"""The closed loop: actors generate, the learner trains, weights flow.

``run_rl_loop`` wires the pieces into the Podracer/Sebulba shape
(arXiv:2104.06272): rollout actors on one side (each an
:class:`~ray_tpu.inference.InferenceEngine` replica), policy-gradient
learner(s) on the other (:func:`~ray_tpu.models.training.
build_gpt_rl_train`, optionally hosted on the RLlib
:class:`~ray_tpu.rllib.core.learner_group.LearnerGroup`), meeting
through :class:`~ray_tpu.rl.replay.WeightStore` (versioned snapshots,
object store when a session is up) and
:class:`~ray_tpu.rl.replay.ReplayQueue` (bounded, hard staleness
bound).  The driver sequences one producer/consumer round per learner
step — actors re-sync to the latest publication before every rollout,
so actor-side lag is bounded by the publish cadence and queue-side lag
by ``max_lag``, deterministically (fixed seeds reproduce the whole
loop, which is what makes the reward-improves acceptance test and the
host-sim bench meaningful).

The default task is the programmatic length-penalized target-token
reward (:mod:`ray_tpu.rl.reward`) — an easy smooth objective whose
expected value must rise under a correct policy gradient.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.rl.config import RLConfig, rl_config
from ray_tpu.rl.learner import InProcessLearner, LearnerGroupAdapter
from ray_tpu.rl.replay import ReplayQueue, WeightStore
from ray_tpu.rl.reward import target_token_reward
from ray_tpu.rl.rollout import RolloutActor


def run_rl_loop(cfg, *, steps: int,
                rlcfg: Optional[RLConfig] = None,
                reward_fn: Optional[Callable] = None,
                prompt: Optional[Sequence[int]] = None,
                prompt_source=None,
                prompt_len: int = 4,
                eos_token: Optional[int] = None,
                seed: int = 0,
                lr: float = 1e-3,
                mesh=None,
                optimizer=None,
                num_learners: int = 0,
                engine_kwargs: Optional[Dict[str, Any]] = None,
                telemetry: Optional[bool] = None) -> Dict[str, Any]:
    """Run ``steps`` learner updates of the actor/learner loop.

    ``num_learners=0`` runs the learner in-process (host-sim parity
    tests, ``bench.py --rl``); ``>= 1`` hosts it on the RLlib
    LearnerGroup (requires an initialized ray_tpu session) with the
    group's object-store snapshot as the publication path.  Engines
    across actor replicas share one executable cache.

    ``prompt_source``: a :class:`~ray_tpu.data.DocumentSource` (or a
    prebuilt :class:`~ray_tpu.rl.rollout.PromptDataset`) — each
    learner round draws its ``rlcfg.batch`` prompts from the
    deterministic r17 document schedule instead of repeating one fixed
    prompt; the final prompt cursor is returned as
    ``result["prompt_cursor"]`` for preemption-proof resume.

    Returns a result dict: per-step ``history`` (learner metrics +
    rollout reward), the ``reward_curve`` (rollout-side mean reward
    per learner step — the policy-improvement signal), queue/staleness
    counters, the telemetry summary and final engine stats.
    """
    rlcfg = rlcfg or rl_config()
    rng = np.random.RandomState(seed)
    prompt_ds = None
    if prompt_source is not None:
        from ray_tpu.rl.rollout import PromptDataset
        prompt_ds = (prompt_source
                     if isinstance(prompt_source, PromptDataset)
                     else PromptDataset(prompt_source,
                                        prompt_len=prompt_len))
        prompt_len = prompt_ds.prompt_len
    if prompt is None:
        prompt = [int(t) for t in
                  rng.randint(0, cfg.vocab_size, prompt_len)]
    prompts = [list(prompt)] * rlcfg.batch   # shared context: RLOO's
    seq_len = len(prompt) + rlcfg.horizon    # leave-one-out wants it
    if reward_fn is None:
        target = int(rng.randint(0, cfg.vocab_size))
        reward_fn = target_token_reward(target,
                                        length_penalty=1.0 / max(
                                            rlcfg.horizon, 1),
                                        eos_token=eos_token)

    from ray_tpu.telemetry.rl import RLTelemetry
    tel = RLTelemetry(config=None if telemetry is None else
                      _tel_config(telemetry))

    if num_learners >= 1:
        if rlcfg.batch % num_learners:
            # LearnerGroup.update trims the batch to a multiple of the
            # world size — a non-dividing batch would silently discard
            # trajectories (actor compute) on every learner step
            raise ValueError(
                f"rollout batch {rlcfg.batch} is not divisible by "
                f"num_learners={num_learners}: the learner group would "
                "silently drop the remainder rows every step "
                "(RAY_TPU_RL_BATCH)")
        if optimizer is not None or mesh is not None:
            # silently training with a different optimizer/mesh than
            # the caller pinned would invalidate any A/B against the
            # in-process arm — refuse instead
            raise ValueError("optimizer/mesh overrides are in-process-"
                             "learner options; the LearnerGroup-hosted "
                             "path (num_learners >= 1) builds its own "
                             "per-actor mesh and adam optimizer (lr=)")
        learner = LearnerGroupAdapter(cfg, num_learners=num_learners,
                                      baseline=rlcfg.baseline, lr=lr,
                                      seed=seed)
    else:
        learner = InProcessLearner(cfg, mesh=mesh,
                                   baseline=rlcfg.baseline, lr=lr,
                                   optimizer=optimizer, seed=seed)
    store = WeightStore(use_object_store=num_learners >= 1)
    # put_timeout pinned to 0: this driver runs producer and consumer
    # on one thread, so a timed put (RAY_TPU_RL_PUT_TIMEOUT) would
    # wait for a pop that cannot happen until it returns — the
    # hold-and-retry `pending` mechanism below is the backpressure
    # path here
    queue = ReplayQueue(rlcfg.queue, max_lag=rlcfg.max_lag,
                        overflow=rlcfg.overflow, put_timeout=0)

    def publish():
        t0 = time.monotonic()
        if isinstance(learner, LearnerGroupAdapter):
            version, ref = learner.publish_ref()
            version = store.publish(ref, version=version)
        else:
            version = store.publish(learner.params_host())
        tel.record_publish(time.monotonic() - t0, version=version)
        return version

    publish()                                # version 1 seeds actors
    _, params0 = store.latest()
    shared_exec: Dict[Any, Any] = {}
    ekw = dict(engine_kwargs or {})
    ekw.setdefault("executable_cache", shared_exec)
    ekw.setdefault("telemetry", False)
    actors = [RolloutActor(cfg, params0, actor_id=i,
                           temperature=rlcfg.temperature,
                           eos_token=eos_token, engine_kwargs=ekw)
              for i in range(rlcfg.actors)]
    for actor in actors:
        actor.engine.param_version = store.version

    history: List[Dict[str, float]] = []
    reward_curve: List[float] = []
    learner_steps = 0
    rollout_seed = seed * 1_000_003
    # under the "wait" overflow policy a rejected put means
    # backpressure: the actor holds its batch and retries before
    # rolling a new one (no trajectory silently discarded)
    pending: Dict[int, Any] = {}
    try:
        while learner_steps < steps:
            # -------- held batches first: a held batch is strictly
            # older than any fresh rollout, so it must win the freed
            # queue space — retrying inline per-actor would let
            # earlier actors re-fill the queue every round and starve
            # the held one forever
            for aid in list(pending):
                if queue.put(pending[aid]):
                    del pending[aid]
                else:
                    tel.record_backpressure()
            # -------- actor side: one rollout per replica, freshest
            # params first (the actor-side staleness contract: sync
            # before every rollout, so an actor's params never lag the
            # latest publication at generation time)
            for actor in actors:
                if actor.actor_id in pending:
                    continue                # backpressured: no rollout
                if actor.param_version != store.version:
                    version, params = store.latest()
                    actor.sync(version, params)
                rollout_seed += rlcfg.batch
                if prompt_ds is not None:
                    prompts = prompt_ds.next_prompts(rlcfg.batch)
                batch = actor.rollout(prompts, horizon=rlcfg.horizon,
                                      seq_len=seq_len,
                                      reward_fn=reward_fn,
                                      seed=rollout_seed)
                tel.record_rollout(batch.wall_s,
                                   tokens=batch.gen_tokens,
                                   param_version=batch.param_version)
                if not queue.put(batch):
                    tel.record_backpressure()
                    pending[actor.actor_id] = batch
            # -------- learner side: drain what is fresh enough
            while learner_steps < steps:
                batch = queue.pop(store.version)
                if batch is None:
                    break
                lag = store.version - batch.param_version
                t0 = time.monotonic()
                metrics = learner.update(batch.as_learner_batch())
                tel.record_learner_step(time.monotonic() - t0,
                                        version_lag=lag)
                learner_steps += 1
                metrics["rollout_reward_mean"] = float(
                    np.mean(batch.rewards))
                metrics["param_version_lag"] = float(lag)
                history.append(metrics)
                reward_curve.append(metrics["rollout_reward_mean"])
                if learner_steps % rlcfg.publish_every == 0:
                    publish()
    finally:
        leftover = queue.drain() + list(pending.values())
        if isinstance(learner, LearnerGroupAdapter):
            learner.stop()
    tel.record_queue_counters(drops_stale=queue.drops_stale,
                              drops_overflow=queue.drops_overflow)
    leaked = [a.actor_id for a in actors if not a.idle()]
    if leaked:
        # a real check, not an assert: it must survive python -O, and
        # a slot/page leak here means the engine invariants broke
        raise RuntimeError(f"rollout engines {leaked} did not drain "
                           "clean at shutdown (slots/pages still held)")
    return {
        "steps": learner_steps,
        "history": history,
        "reward_curve": reward_curve,
        "leftover_batches": len(leftover),
        "drops_stale": queue.drops_stale,
        "drops_overflow": queue.drops_overflow,
        "param_version": store.version,
        "publishes": store.publish_count,
        "telemetry": tel.summary(),
        "engine_stats": [a.engine.stats() for a in actors],
        "actors": [a.engine for a in actors],
        "learner": learner,
        "prompt_cursor": (prompt_ds.cursor_array()
                          if prompt_ds is not None else None),
    }


def _tel_config(enabled: bool):
    from ray_tpu.telemetry.config import TelemetryConfig
    return TelemetryConfig(enabled=bool(enabled))
