"""``ray_tpu.inference`` — TPU-native continuous-batching inference.

The serving-side counterpart of ``ray_tpu.models.training``: a paged KV
cache (:mod:`~ray_tpu.inference.kv_cache`), bucketed AOT-compiled
prefill + fixed-slot decode steps (:mod:`~ray_tpu.inference.engine`),
a host-side continuous-batching scheduler
(:mod:`~ray_tpu.inference.scheduler`), per-sequence-PRNG sampling
(:mod:`~ray_tpu.inference.sampling`) and a ``serve`` deployment that
streams tokens through ``handle_request_streaming``
(:mod:`~ray_tpu.inference.serve_gpt`).  Config via ``RAY_TPU_INFER_*``
(:func:`infer_config`).
"""

from ray_tpu.inference.config import (InferConfig,  # noqa: F401
                                      infer_config, default_buckets)
from ray_tpu.inference.engine import (InferenceEngine,  # noqa: F401
                                      StepEvent)
from ray_tpu.inference.kv_cache import (HandoffContentMissing,  # noqa: F401
                                        HostPagePool, KVCache,
                                        KVHandoff, KVPageStore,
                                        PageAllocator, PrefixIndex)
from ray_tpu.inference.sampling import SamplingParams  # noqa: F401
from ray_tpu.inference.scheduler import (DeadlineExceededError,  # noqa: F401
                                         QueueFullError,
                                         Request, SlotScheduler)
from ray_tpu.inference.spec import DraftState  # noqa: F401

__all__ = [
    "InferConfig", "infer_config", "default_buckets",
    "InferenceEngine", "StepEvent", "KVCache", "PageAllocator",
    "PrefixIndex", "KVHandoff", "HandoffContentMissing",
    "HostPagePool", "KVPageStore",
    "SamplingParams", "QueueFullError", "DeadlineExceededError",
    "Request", "SlotScheduler", "DraftState",
]
