"""Continuous-batching scheduler: slots, pages, request lifecycle.

Host-side state machine beside the compiled steps (the Podracer
pattern: a python scheduler colocated with AOT-compiled device step
functions).  Requests move ``waiting -> active(slot) -> finished``:

- **admit**: the head of the waiting queue takes a free decode slot and
  reserves ``ceil((prompt + max_new) / page_size)`` pages up front —
  reservation-at-admission means a running sequence can never run out
  of cache mid-decode, so there is no preemption path to get wrong.
  Admission blocks (request stays queued) until both a slot and the
  pages are free.
- **retire** (EOS / max-new-tokens): pages return to the free list, the
  page-table row resets to the garbage page, the slot frees.

The page table and per-slot lengths live here as numpy arrays and are
passed into the fixed-shape compiled steps each call; the engine owns
the device-side cache arrays.  Invariants (no slot/page leaks across
any admit/retire interleaving) are fuzzed in
``tests/test_inference.py``.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Deque, Dict, List, Optional

import numpy as np

from ray_tpu.inference.kv_cache import (GARBAGE_PAGE, PageAllocator,
                                        pages_needed)
from ray_tpu.inference.sampling import SamplingParams


@dataclasses.dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    sampling: SamplingParams
    eos_token: Optional[int] = None
    # lifecycle state (owned by the scheduler/engine)
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    pages: Optional[List[int]] = None
    submitted_ts: float = dataclasses.field(default_factory=time.monotonic)
    done: bool = False


class SlotScheduler:
    def __init__(self, *, slots: int, page_size: int, num_pages: int,
                 max_pages_per_slot: int):
        self.slots = slots
        self.page_size = page_size
        self.max_pages_per_slot = max_pages_per_slot
        self.allocator = PageAllocator(num_pages)
        self.page_table = np.full((slots, max_pages_per_slot),
                                  GARBAGE_PAGE, np.int32)
        self.lengths = np.zeros((slots,), np.int32)   # tokens in cache
        self.free_slots: List[int] = list(range(slots - 1, -1, -1))
        self.active: Dict[int, Request] = {}          # slot -> request
        self.waiting: Deque[Request] = collections.deque()

    # ------------------------------------------------------------ admit
    def submit(self, req: Request) -> None:
        need = pages_needed(len(req.prompt) + req.max_new_tokens,
                            self.page_size)
        if need > self.max_pages_per_slot:
            raise ValueError(
                f"request {req.rid}: prompt+max_new = "
                f"{len(req.prompt) + req.max_new_tokens} tokens needs "
                f"{need} pages > {self.max_pages_per_slot} per slot")
        # an unsatisfiable-even-when-idle request must raise, not queue:
        # FIFO admission would otherwise spin on it forever (page 0 is
        # reserved, so the whole pool is num_pages - 1)
        if need > self.allocator.num_pages - 1:
            raise ValueError(
                f"request {req.rid}: needs {need} pages but the pool "
                f"only has {self.allocator.num_pages - 1} "
                f"(raise RAY_TPU_INFER_PAGES or shrink the request)")
        self.waiting.append(req)

    def try_admit(self) -> Optional[Request]:
        """Move the queue head into a free slot, or None (FIFO: a large
        stuck head does not get bypassed by smaller requests — simple
        and starvation-free)."""
        if not self.waiting or not self.free_slots:
            return None
        req = self.waiting[0]
        need = pages_needed(len(req.prompt) + req.max_new_tokens,
                            self.page_size)
        pages = self.allocator.alloc(need)
        if pages is None:
            return None
        self.waiting.popleft()
        slot = self.free_slots.pop()
        req.slot, req.pages = slot, pages
        self.page_table[slot, :] = GARBAGE_PAGE
        self.page_table[slot, :len(pages)] = pages
        self.lengths[slot] = 0
        self.active[slot] = req
        return req

    # ----------------------------------------------------------- retire
    def retire(self, slot: int) -> Request:
        req = self.active.pop(slot)
        self.allocator.free(req.pages)
        req.pages = None
        req.slot = None
        req.done = True
        self.page_table[slot, :] = GARBAGE_PAGE
        self.lengths[slot] = 0
        self.free_slots.append(slot)
        return req

    # ------------------------------------------------------------ views
    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.active)
