"""Continuous-batching scheduler: slots, pages, request lifecycle.

Host-side state machine beside the compiled steps (the Podracer
pattern: a python scheduler colocated with AOT-compiled device step
functions).  Requests move ``waiting -> active(slot) -> finished``:

- **admit**: the head of the waiting queue takes a free decode slot and
  reserves ``ceil((prompt + max_new) / page_size)`` pages up front —
  reservation-at-admission means a running sequence can never run out
  of cache mid-decode, so there is no preemption path to get wrong.
  Admission blocks (request stays queued) until both a slot and the
  pages are free.  With prefix caching on, admission first walks the
  prompt's full pages through the :class:`~.kv_cache.PrefixIndex`:
  hits are installed into the page-table row with refcount bumps and
  **zero prefill compute**; only the pages past the last hit are
  freshly allocated, and the engine prefills only the uncached suffix.
  With tiering on (r23) the walk keeps going where the resident index
  stops: the remaining hashes are looked up in the host-DRAM spill
  pool and the fleet page store, and every consecutive lower-tier hit
  is planned for promotion — the engine installs those pages into the
  freshly-allocated storage between ticks and prefills only what no
  tier holds.
- **retire** (EOS / max-new-tokens): the request's page references are
  released — shared pages survive under their other owners' refcounts,
  registered refcount-0 pages park in the allocator's idle pool, the
  rest return to the free list; the page-table row resets to the
  garbage page and the slot frees.

Decode writes only ever land in pages the slot *exclusively* owns (the
private tail past the prompt), so copy-on-write reduces to a
never-write-shared invariant: a hit page is always a full prompt page
strictly before the final prompt token, and the suffix prefill's first
write position is ``cached_tokens`` — on a page boundary past every
shared page.

**Load shedding**: ``max_queue`` (``RAY_TPU_INFER_MAX_QUEUE``) caps the
waiting queue; over-cap submits raise :class:`QueueFullError` — a typed
rejection the serve deployment surfaces as the stream's error — instead
of queueing unboundedly.

The page table and per-slot lengths live here as numpy arrays and are
passed into the fixed-shape compiled steps each call; the engine owns
the device-side cache arrays.  Invariants (no slot/page leaks, no page
freed while referenced, across any admit/hit/retire/evict interleaving)
are fuzzed in ``tests/test_inference.py``.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Deque, Dict, List, Optional

import numpy as np

from ray_tpu.inference.kv_cache import (GARBAGE_PAGE, PageAllocator,
                                        PrefixIndex, pages_needed)
from ray_tpu.inference.sampling import SamplingParams


class QueueFullError(RuntimeError):
    """Typed admission rejection: the waiting queue is at
    ``RAY_TPU_INFER_MAX_QUEUE`` — shed load (retry later / another
    replica) instead of queueing unboundedly."""


class DeadlineExceededError(RuntimeError):
    """Typed per-request deadline expiry (``RAY_TPU_INFER_TTFT_DEADLINE``
    / ``RAY_TPU_INFER_DEADLINE`` or per-request overrides): the request
    was retired — slot, pages and prefix refcounts released — because
    it blew its time-to-first-token or total budget.  Surfaced as the
    stream's error; wedged or over-deadline work is shed, not queued
    (the arXiv:2011.03641 concurrency-limits argument in seconds)."""

    def __init__(self, rid: int, kind: str, budget_s: float,
                 waited_s: float):
        super().__init__(
            f"request {rid}: {kind} deadline of {budget_s:.3f}s "
            f"exceeded ({waited_s:.3f}s elapsed)")
        self.rid = rid
        self.kind = kind            # "ttft" | "total"
        self.budget_s = budget_s
        self.waited_s = waited_s

    def __reduce__(self):
        # default exception pickling replays __init__ with self.args
        # (the message) — this error crosses the object store on serve
        # streams, so it must rebuild from its real constructor args
        return (DeadlineExceededError,
                (self.rid, self.kind, self.budget_s, self.waited_s))


@dataclasses.dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    sampling: SamplingParams
    eos_token: Optional[int] = None
    # lifecycle state (owned by the scheduler/engine)
    generated: List[int] = dataclasses.field(default_factory=list)
    # chosen-token model logprobs, one per generated token (see
    # ``sampling``: log_softmax of the raw f32 logits at the sampled
    # id — the quantity the RL actors and the serve logprobs option
    # consume)
    logprobs: List[float] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    pages: Optional[List[int]] = None
    submitted_ts: float = dataclasses.field(default_factory=time.monotonic)
    admitted_ts: Optional[float] = None
    done: bool = False
    # deadlines (seconds from submit; None = none): ``ttft_deadline_s``
    # bounds time-to-first-token — it can only expire while the request
    # is still waiting, because admission delivers the first token in
    # the same tick — and ``deadline_s`` bounds the whole request.  An
    # expired request is retired with everything released and carries
    # the typed error here for the stream to surface.
    ttft_deadline_s: Optional[float] = None
    deadline_s: Optional[float] = None
    error: Optional[BaseException] = None
    # prefix-cache state: chained hashes of the prompt's full pages
    # (None until the first admission attempt computes them — they are
    # immutable per request, so retries reuse them), how many were
    # index hits, and the token count the hits cover (skipped prefill)
    chain_hashes: Optional[List[bytes]] = None
    n_hit_pages: int = 0
    cached_tokens: int = 0
    # disaggregated serving (r20): ``hold_pages`` keeps the request's
    # page references refcounted past retirement (the prefill-side
    # export seam — released by export_request/release_held); a
    # non-None ``import_payload`` (a kv_cache.KVHandoff) marks a
    # decode-side import, admitted like any request but installed from
    # the payload instead of prefilled
    hold_pages: bool = False
    import_payload: Optional[Any] = None
    # tiered cache (r23): how many eligible pages past the resident
    # hits a lower tier (host pool / page store) held at admission —
    # the engine promotes them into the fresh pages between ticks and
    # converts each success into a hit via ``note_tier_hits``; any
    # fetch failure just leaves the page to the suffix prefill
    tier_plan: int = 0
    # speculative decoding (r21): the resolved draft budget for this
    # request — 0 = plain decode; > 0 = up to this many self-drafted
    # tokens verified per engine tick.  Resolved at submit time from
    # ``SamplingParams.spec``/``spec_k`` overriding the engine
    # defaults, so the scheduler and engine never re-consult config.
    spec_k: int = 0
    # distributed tracing (r24): the request's TraceContext (a
    # telemetry.trace.TraceContext, None = untraced) — minted at the
    # router/serve boundary, carried here so every lifecycle stage can
    # hang spans off the same trace_id
    trace: Optional[Any] = None
    # multi-tenant serving (r25): the adapter this request decodes
    # under (None = base).  ``adapter_slot`` is the engine's bank row:
    # 0 = the identity slot, -1 = not yet resolved (the engine loads
    # the adapter and pins it before this request's first admission
    # attempt); ``adapter_version`` pins the store version (0 = latest,
    # resolved in place).  ``hash_salt`` overrides the prefix-chain
    # root so adapter K/V never aliases base K/V in the index/tiers —
    # it MUST be set before the first ``_prefix_walk`` computes
    # ``chain_hashes``.
    model_id: Optional[str] = None
    adapter_slot: int = 0
    adapter_version: int = 0
    hash_salt: bytes = b""


class SlotScheduler:
    def __init__(self, *, slots: int, page_size: int, num_pages: int,
                 max_pages_per_slot: int, prefix: bool = False,
                 max_queue: int = 0):
        self.slots = slots
        self.page_size = page_size
        self.max_pages_per_slot = max_pages_per_slot
        self.prefix_index = PrefixIndex() if prefix else None
        self.allocator = PageAllocator(num_pages,
                                       index=self.prefix_index)
        self.max_queue = max_queue
        self.page_table = np.full((slots, max_pages_per_slot),
                                  GARBAGE_PAGE, np.int32)
        self.lengths = np.zeros((slots,), np.int32)   # tokens in cache
        self.free_slots: List[int] = list(range(slots - 1, -1, -1))
        self.active: Dict[int, Request] = {}          # slot -> request
        self.waiting: Deque[Request] = collections.deque()
        # prefix-hit accounting (tokens = pages * page_size: the
        # prefill compute the hits skipped)
        self.prefix_hit_pages = 0
        self.prefix_hit_tokens = 0
        self.prefix_requests_hit = 0
        # r23: engine-installed probe over the lower tiers —
        # ``tier_lookup(chain_hash) -> bool`` (does the host pool or
        # the fleet store hold this hash under the live params?)
        self.tier_lookup = None

    # ------------------------------------------------------------ admit
    def submit(self, req: Request) -> None:
        need = pages_needed(len(req.prompt) + req.max_new_tokens,
                            self.page_size)
        if need > self.max_pages_per_slot:
            raise ValueError(
                f"request {req.rid}: prompt+max_new = "
                f"{len(req.prompt) + req.max_new_tokens} tokens needs "
                f"{need} pages > {self.max_pages_per_slot} per slot")
        # an unsatisfiable-even-when-idle request must raise, not queue:
        # FIFO admission would otherwise spin on it forever (page 0 is
        # reserved, so the whole pool is num_pages - 1)
        if need > self.allocator.num_pages - 1:
            raise ValueError(
                f"request {req.rid}: needs {need} pages but the pool "
                f"only has {self.allocator.num_pages - 1} "
                f"(raise RAY_TPU_INFER_PAGES or shrink the request)")
        if self.max_queue and len(self.waiting) >= self.max_queue:
            raise QueueFullError(
                f"request {req.rid}: waiting queue at its cap of "
                f"{self.max_queue} (RAY_TPU_INFER_MAX_QUEUE) — "
                "shedding load instead of queueing unboundedly")
        self.waiting.append(req)

    def _prefix_walk(self, req: Request) -> List[int]:
        """Walk the prompt's full pages through the index and return
        the hit pages — a prefix of the full pages, stopped at the
        first miss.  The chained hashes are immutable per request, so
        the first attempt computes and caches them on the request and
        pool-pressure retries only re-do the (cheap) lookups — which
        *must* re-run: pages registered since the last attempt can
        turn misses into hits.

        Registrable pages are those fully covered by the prompt
        (boundary <= prompt length: decode writes start at position
        ``plen``, so they are immutable).  *Hit-eligible* pages stop
        one token earlier — the page holding the final prompt token is
        never taken as a hit even when full, because that token's
        logits seed the first sampled token, so at least one suffix
        token must always prefill."""
        if self.prefix_index is None:
            req.chain_hashes = req.chain_hashes or []
            return []
        if req.chain_hashes is None:
            req.chain_hashes = PrefixIndex.chain_hashes(
                req.prompt, self.page_size, salt=req.hash_salt)
        hits: List[int] = []
        # an imported request (r20 disagg) never prefills: EVERY full
        # context page is hit-eligible, including the one holding the
        # final context token — its logits were already consumed on the
        # prefill side, so nothing here needs to re-run
        eligible = (len(req.chain_hashes)
                    if req.import_payload is not None
                    else PrefixIndex.hit_eligible(len(req.prompt),
                                                  self.page_size))
        for h_i in req.chain_hashes[:eligible]:
            page = self.prefix_index.lookup(h_i)
            if page is None:
                break
            hits.append(page)
        # r23: walk the remaining eligible hashes through the lower
        # tiers (host pool, then the fleet store — the probe hides the
        # order).  Recomputed on every attempt like the resident walk:
        # demotions since the last attempt can move hits between
        # tiers, and promotions can turn them resident.  The plan is
        # advisory — the engine re-resolves each page at install time
        # and degrades any miss or fault to plain prefill.
        req.tier_plan = 0
        if self.tier_lookup is not None and req.import_payload is None:
            for h_i in req.chain_hashes[len(hits):eligible]:
                if not self.tier_lookup(h_i):
                    break
                req.tier_plan += 1
        return hits

    def try_admit(self) -> Optional[Request]:
        """Move the queue head into a free slot, or None (FIFO: a large
        stuck head does not get bypassed by smaller requests — simple
        and starvation-free)."""
        if not self.waiting or not self.free_slots:
            return None
        req = self.waiting[0]
        need = pages_needed(len(req.prompt) + req.max_new_tokens,
                            self.page_size)
        walk_t0 = time.monotonic()
        hits = self._prefix_walk(req)
        walk_dur = time.monotonic() - walk_t0
        # exact feasibility check before touching any state: acquiring
        # the hits removes the idle ones from the allocatable pool, so
        # the fresh allocation needs that much headroom beyond them —
        # failing here keeps a blocked head from churning refcounts
        # and idle-LRU order on every tick
        idle_hits = sum(1 for p in hits if self.allocator.is_idle(p))
        if need - len(hits) > self.allocator.free_count - idle_hits:
            return None
        # acquire hits BEFORE allocating fresh pages: an idle hit must
        # not be evicted by our own allocation's LRU sweep
        for p in hits:
            self.allocator.acquire(p)
        fresh = self.allocator.alloc(need - len(hits))
        assert fresh is not None        # guaranteed by the check above
        self.waiting.popleft()
        slot = self.free_slots.pop()
        pages = hits + fresh
        req.slot, req.pages = slot, pages
        req.n_hit_pages = len(hits)
        req.cached_tokens = len(hits) * self.page_size
        req.admitted_ts = time.monotonic()
        self.page_table[slot, :] = GARBAGE_PAGE
        self.page_table[slot, :len(pages)] = pages
        self.lengths[slot] = 0
        self.active[slot] = req
        if hits:
            self.prefix_hit_pages += len(hits)
            self.prefix_hit_tokens += req.cached_tokens
            self.prefix_requests_hit += 1
        if req.trace is not None and req.trace.sampled:
            # only the admitting walk is recorded — blocked attempts
            # re-walk but never admit, and a span per blocked tick
            # would drown the ring
            from ray_tpu.telemetry import trace as _trace
            _trace.record_span(
                "prefix_walk", req.trace,
                start=_trace.epoch_of(walk_t0), dur=walk_dur,
                hits=len(hits), tier_plan=req.tier_plan,
                eligible=len(req.chain_hashes or []))
        return req

    def note_tier_hits(self, req: Request, n_pages: int) -> None:
        """Account ``n_pages`` lower-tier promotions the engine just
        installed for ``req`` (between admission and its prefill):
        the request's cached window grows page-aligned, and the shared
        prefix counters treat promoted pages exactly like resident
        hits — they skipped the same prefill compute.  The request
        joins ``requests_hit`` only if the resident walk found nothing
        (it was already counted otherwise)."""
        if n_pages <= 0:
            return
        if req.n_hit_pages == 0:
            self.prefix_requests_hit += 1
        req.n_hit_pages += n_pages
        req.cached_tokens += n_pages * self.page_size
        self.prefix_hit_pages += n_pages
        self.prefix_hit_tokens += n_pages * self.page_size

    def register_prefix(self, req: Request) -> None:
        """Register the request's freshly-prefilled full prompt pages
        in the index (the engine calls this *after* the prefill
        executable has written their K/V — content must be in cache
        before a hash can hand the page to another request)."""
        if self.prefix_index is None:
            return
        for i in range(req.n_hit_pages, len(req.chain_hashes)):
            self.prefix_index.register(req.chain_hashes[i],
                                       req.pages[i])

    def flush_prefix(self) -> None:
        """Invalidate the whole prefix cache (weight swap: every
        cached K/V page was computed under the OLD params, and the
        index is keyed by token content alone, so a post-swap lookup
        would happily serve stale attention context).  Idle pages go
        back to the free list; pages still referenced by active
        sequences stay allocated (those sequences are mid-flight under
        the old weights by the caller's choice) but are unregistered,
        so no *new* request can share them — they free normally at
        retire.  Queued requests re-run their (now-missing) lookups at
        the next admission attempt."""
        if self.prefix_index is None:
            return
        self.allocator.flush_idle()
        self.prefix_index.clear()

    # ----------------------------------------------------------- retire
    def retire(self, slot: int) -> Request:
        req = self.active.pop(slot)
        self.allocator.release(req.pages)
        req.pages = None
        req.slot = None
        req.done = True
        self.page_table[slot, :] = GARBAGE_PAGE
        self.lengths[slot] = 0
        self.free_slots.append(slot)
        return req

    def retire_hold(self, slot: int) -> Request:
        """Retire like :meth:`retire` but KEEP the request's page
        references (``req.pages`` stays set, refcounts unmoved) — the
        disaggregation export seam: the slot frees for the next
        admission while the cached K/V survives for
        ``export_request``.  The engine owns the held request from
        here; the leak audit stays red until the pages are released
        (export or the failure path), which is exactly how orphaned
        exports are caught."""
        req = self.active.pop(slot)
        req.slot = None
        req.done = True
        self.page_table[slot, :] = GARBAGE_PAGE
        self.lengths[slot] = 0
        self.free_slots.append(slot)
        return req

    # ------------------------------------------------------------ views
    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.active)

    def prefix_stats(self) -> Dict[str, Any]:
        return {
            "enabled": self.prefix_index is not None,
            "hit_pages": self.prefix_hit_pages,
            "hit_tokens": self.prefix_hit_tokens,
            "requests_hit": self.prefix_requests_hit,
            "registered_pages": (len(self.prefix_index)
                                 if self.prefix_index is not None
                                 else 0),
            "idle_pages": self.allocator.idle_count,
            "evictions": self.allocator.evictions,
        }

    def prefix_digest(self) -> frozenset:
        """Registered-chain-hash snapshot for fleet prefix-affinity
        routing (empty when the prefix cache is off)."""
        if self.prefix_index is None:
            return frozenset()
        return self.prefix_index.digest()
