"""Inference-engine env knobs — the single home for serving config.

Follows the ``attention_config()`` / ``ce_config()`` / ``comm_config()``
/ ``telemetry_config()`` precedent: one frozen dataclass resolved from
the environment once, ``refresh=True`` for tests and A/B drivers that
flip flags after import.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class InferConfig:
    """Inference-engine knobs, resolved once from the environment.

    - ``RAY_TPU_INFER_SLOTS`` (default ``8``): decode batch slots — the
      fixed batch dimension of the compiled decode step.  Continuous
      batching admits/retires sequences into these slots without
      changing the compiled shape.
    - ``RAY_TPU_INFER_PAGE_SIZE`` (default ``128``): tokens per KV-cache
      page.  128 keeps a slot's gathered context a multiple of the
      decode kernel's 128-lane strip.
    - ``RAY_TPU_INFER_PAGES`` (default ``0`` = auto): total pages in the
      preallocated cache.  Auto sizes for every slot at full context
      (``slots * ceil(max_seq / page_size)``) plus the reserved garbage
      page; set lower to trade admission concurrency for HBM.
    - ``RAY_TPU_INFER_BUCKETS`` (default unset = powers of two from 32
      up to the model's ``max_seq``): comma-separated prefill length
      buckets.  Prompts are padded up to the smallest bucket that fits,
      so arbitrary request lengths hit at most ``len(buckets)`` prefill
      compiles and the decode step exactly one.
    - ``RAY_TPU_INFER_DECODE`` (default ``auto``): decode-attention
      implementation — ``pallas`` (strip-mined online-softmax kernel,
      ``ops/attention.py:decode_attention``), ``xla`` (masked einsum),
      or ``auto`` (pallas on a TPU backend when the context tiles).
    - ``RAY_TPU_KV_DTYPE`` (default ``model``): KV-cache storage dtype
      — ``model`` (the model's ``cfg.dtype``) or ``int8``
      (block-scaled int8, one f32 scale per (position, head) lane
      vector stored in per-page scale arrays; keys/values quantize
      post-RoPE on write and dequantize inside the decode-attention
      context strips).  ``int8`` roughly halves ``KVCache.bytes`` per
      page — i.e. ~2x the decode slots per HBM byte — at a bounded
      logits error (parity-tested against the ``model``-dtype cache).
      Default stays ``model`` until the on-chip A/B
      (``scratch/r11_quant.py``).
    - ``RAY_TPU_INFER_PREFIX`` (default ``1``): content-addressed
      prefix caching — full prompt pages register in a host-side
      chained-hash index and later requests sharing the prefix install
      the hit pages with refcount bumps, prefilling only the uncached
      suffix (one cached-context prefill executable per suffix bucket;
      zero steady-state recompiles still hold).  Pure host-side page-
      table metadata plus an XLA masked-einsum attention path — exact
      in model dtype (parity-tested), so it defaults on; ``0`` reverts
      to full-prompt prefill for every request.
    - ``RAY_TPU_INFER_MAX_QUEUE`` (default ``0`` = unbounded): cap on
      the scheduler's waiting queue.  Over-cap submits raise a typed
      :class:`~ray_tpu.inference.scheduler.QueueFullError` (load
      shedding) that the serve deployment surfaces as the stream's
      error instead of queueing unboundedly.
    - ``RAY_TPU_INFER_TTFT_DEADLINE`` (default ``0`` = none): default
      per-request time-to-first-token deadline in seconds.  A request
      still waiting past it is retired with a typed
      :class:`~ray_tpu.inference.scheduler.DeadlineExceededError`
      surfaced on its stream — over-deadline work is shed, not queued.
    - ``RAY_TPU_INFER_DEADLINE`` (default ``0`` = none): default
      per-request *total* deadline in seconds (submit to last token);
      expiry mid-decode retires the sequence, releasing its slot,
      pages and prefix refcounts.
    - ``RAY_TPU_INFER_WATCHDOG`` (default ``0`` = off): engine
      watchdog timeout in seconds — with work pending and no engine
      tick completing for this long, the serve replica's
      :class:`~ray_tpu.resilience.watchdog.EngineWatchdog` declares
      the step loop wedged (stderr + ``wedges`` counter; the drain /
      restart decision is the operator's).
    - ``RAY_TPU_INFER_STREAM_IDLE`` (default ``0`` = off): idle-
      consumer timeout in seconds for serve streams.  A consumer that
      silently drops its response generator is undetectable through
      the object-ref streaming protocol (no liveness signal); with
      this set, the deployment cancels any request whose stream has
      tokens waiting but has not been pumped for the budget —
      releasing its slot/pages/prefix refcounts instead of decoding
      to ``max_new_tokens`` for a reader that is gone.
    - ``RAY_TPU_INFER_SPEC`` (default ``0`` = off): speculative
      decoding default — the zero-parameter self-drafter proposes up
      to ``spec_k`` continuation tokens per slot from the request's
      own context and one batched verify forward (the cached-context
      prefill executable, per k-bucket) scores them all; exact
      acceptance sampling keeps outputs distribution-identical to
      plain decode (greedy bit-exact, sampled trajectory-exact).
      Per-request ``SamplingParams.spec`` overrides win.
    - ``RAY_TPU_INFER_SPEC_K`` (default ``4``): default draft length
      cap per verify step when speculation is on.  Per-request
      ``SamplingParams.spec_k`` overrides win.
    - ``RAY_TPU_KV_HOST_PAGES`` (default ``0`` = tiering off): capacity
      in pages of the per-engine host-DRAM spill pool (tier 1).  With
      it set, LRU evictions from HBM *demote* a prefix page's contents
      host-side instead of forgetting them, and admission's prefix
      walk extends through the pool — a later request promotes the
      page back into fresh HBM between ticks at zero prefill compute.
    - ``RAY_TPU_KV_STORE`` (default ``1``): participate in the
      fleet-shared content-addressed page store (tier 2) when tiering
      is on — host-pool overflow demotes on to the store, and
      admission's walk extends through it, so every replica (including
      restarts and scale-from-zero spawns) warms up from pages any
      other replica prefilled.  ``0`` caps the hierarchy at host DRAM.
    - ``RAY_TPU_KV_STORE_CAP`` (default ``0`` = unbounded): byte cap on
      the fleet-shared page store (tier 2).  Over-cap puts evict the
      least-recently-checked-out entries (never one mid-checkout —
      in-flight fetches pin their entry), counted in the store's
      ``evictions`` stat and the ``infer_kv_store_evictions_total``
      counter.  A re-admit whose store pages were evicted degrades to
      suffix prefill — exact continuations, just cold.
    - ``RAY_TPU_KV_SPILL_DTYPE`` (default ``int8``): spill/wire format
      for demoted pages — ``int8`` (per-vector block-scaled codes,
      ``head_dim + 4`` bytes per cached vector: ~2x cheaper DRAM/store
      residency and fetch bytes, the r11/r22 trick applied to the spill
      tier) or ``model`` (raw storage-dtype bytes, exact).  int8
      caches always spill their codes + scales verbatim (already the
      cheapest exact form).
    """
    slots: int = 8
    page_size: int = 128
    pages: int = 0
    buckets: Tuple[int, ...] = ()
    decode_impl: str = "auto"
    kv_dtype: str = "model"
    prefix: bool = True
    max_queue: int = 0
    ttft_deadline: float = 0.0
    deadline: float = 0.0
    watchdog: float = 0.0
    stream_idle: float = 0.0
    spec: bool = False
    spec_k: int = 4
    host_pages: int = 0
    store: bool = True
    store_cap: int = 0
    spill_dtype: str = "int8"


_CONFIG: Optional[InferConfig] = None


def infer_config(refresh: bool = False) -> InferConfig:
    """The process-wide :class:`InferConfig` (env read once, cached)."""
    global _CONFIG
    if _CONFIG is None or refresh:
        env = os.environ.get
        impl = env("RAY_TPU_INFER_DECODE", "auto")
        if impl not in ("auto", "pallas", "xla"):
            print(f"RAY_TPU_INFER_DECODE={impl!r} unknown; using 'auto'",
                  file=sys.stderr)
            impl = "auto"
        raw_buckets = env("RAY_TPU_INFER_BUCKETS", "")
        buckets = tuple(sorted(int(b) for b in raw_buckets.split(",")
                               if b.strip())) if raw_buckets else ()
        kv_dtype = env("RAY_TPU_KV_DTYPE", "model")
        if kv_dtype not in ("model", "int8"):
            print(f"RAY_TPU_KV_DTYPE={kv_dtype!r} unknown; "
                  "using 'model'", file=sys.stderr)
            kv_dtype = "model"
        max_queue = int(env("RAY_TPU_INFER_MAX_QUEUE", "0"))
        if max_queue < 0:
            print(f"RAY_TPU_INFER_MAX_QUEUE={max_queue} negative; "
                  "using 0 (unbounded)", file=sys.stderr)
            max_queue = 0

        def nonneg_float(name, off_meaning):
            val = float(env(name, "0"))
            if val < 0:
                print(f"{name}={val} negative; using 0 "
                      f"({off_meaning})", file=sys.stderr)
                return 0.0
            return val

        ttft_deadline = nonneg_float("RAY_TPU_INFER_TTFT_DEADLINE",
                                     "no TTFT deadline")
        deadline = nonneg_float("RAY_TPU_INFER_DEADLINE",
                                "no total deadline")
        watchdog = nonneg_float("RAY_TPU_INFER_WATCHDOG",
                                "watchdog off")
        stream_idle = nonneg_float("RAY_TPU_INFER_STREAM_IDLE",
                                   "idle-stream reaper off")
        spec_k = int(env("RAY_TPU_INFER_SPEC_K", "4"))
        if spec_k < 1:
            print(f"RAY_TPU_INFER_SPEC_K={spec_k} < 1; using 4",
                  file=sys.stderr)
            spec_k = 4
        host_pages = int(env("RAY_TPU_KV_HOST_PAGES", "0"))
        if host_pages < 0:
            print(f"RAY_TPU_KV_HOST_PAGES={host_pages} negative; "
                  "using 0 (tiering off)", file=sys.stderr)
            host_pages = 0
        store_cap = int(env("RAY_TPU_KV_STORE_CAP", "0"))
        if store_cap < 0:
            print(f"RAY_TPU_KV_STORE_CAP={store_cap} negative; "
                  "using 0 (unbounded)", file=sys.stderr)
            store_cap = 0
        spill_dtype = env("RAY_TPU_KV_SPILL_DTYPE", "int8")
        if spill_dtype not in ("int8", "model"):
            print(f"RAY_TPU_KV_SPILL_DTYPE={spill_dtype!r} unknown; "
                  "using 'int8'", file=sys.stderr)
            spill_dtype = "int8"
        _CONFIG = InferConfig(
            slots=int(env("RAY_TPU_INFER_SLOTS", "8")),
            page_size=int(env("RAY_TPU_INFER_PAGE_SIZE", "128")),
            pages=int(env("RAY_TPU_INFER_PAGES", "0")),
            buckets=buckets,
            decode_impl=impl,
            kv_dtype=kv_dtype,
            prefix=env("RAY_TPU_INFER_PREFIX", "1") != "0",
            max_queue=max_queue,
            ttft_deadline=ttft_deadline,
            deadline=deadline,
            watchdog=watchdog,
            stream_idle=stream_idle,
            spec=env("RAY_TPU_INFER_SPEC", "0") != "0",
            spec_k=spec_k,
            host_pages=host_pages,
            store=env("RAY_TPU_KV_STORE", "1") != "0",
            store_cap=store_cap,
            spill_dtype=spill_dtype,
        )
    return _CONFIG


def default_buckets(max_seq: int, smallest: int = 32) -> Tuple[int, ...]:
    """Powers of two from ``smallest`` up to (and including) ``max_seq``."""
    out = []
    b = min(smallest, max_seq)
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(out)
