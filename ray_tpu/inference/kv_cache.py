"""Paged KV cache for continuous-batching decode.

The cache is two preallocated device arrays per model —
``[n_layers, pages, page_size, kv_heads, head_dim]`` K and V — plus a
*host-side* page table: each decode slot owns a row of page indices
covering its reserved context.  Sequences of wildly different lengths
then share one fixed allocation (the vLLM paged-attention idea, here
XLA-functional): admission reserves ``ceil((prompt + max_new) / page)``
pages from a free list, retirement returns them, and the device arrays
never reallocate — the compiled decode step donates them in and gets
them back, so steady-state decode allocates nothing.

Page 0 is reserved as a garbage page: free slots' page-table rows (and
the padded tail of short rows) point at it, so the fixed-shape decode
step can scatter "writes" for inactive slots and prefill can write its
padded bucket tail without corrupting live pages.  Reads of garbage are
masked by per-slot lengths in ``decode_attention``.

Device-side update/gather helpers are plain functional jnp ops (scatter
via ``.at[]``, gather via advanced indexing) so they trace into the
engine's compiled steps; the host-side :class:`PageAllocator` owns the
refcounts, free structures and the leak invariants
(``tests/test_inference.py``).

**Prefix sharing (r12).**  Full pages are immutable — decode appends
only ever land in the private tail page past the prompt — so a full
prompt page can be *shared* across requests byte-for-byte.
:class:`PrefixIndex` registers full pages under chained content hashes
and :class:`PageAllocator` refcounts every reference; refcount-0
registered pages park in an LRU idle pool that ``alloc`` evicts from
only after the free list runs dry, so the idle cache is reusable
prefix storage rather than dead HBM.  Sharing is pure host-side page-
table metadata: the compiled steps never see it, and ``int8`` caches
share bit-identically because cache writes use deterministic
rounding.

**Disaggregated handoff (r20).**  Because pages are content-addressed
and refcounted, moving a request from a prefill replica to a decode
replica is a transfer of page *ownership*, not a copy protocol:
:func:`export_pages` reads a retired-but-held request's page contents
host-side into a :class:`KVHandoff` (context tokens + chained hashes +
raw K/V; int8 codes and scales ride the same arrays, halving the
bytes vs bf16), and :func:`import_pages` writes only the pages the
importing engine does *not* already hold by chain hash into its own
allocator's fresh pages — a warm importer installs the whole context
as prefix hits and the handoff moves no contents at all.

``kv_dtype="int8"`` stores the K/V arrays block-scale-quantized
(``ray_tpu.quant``): codes in int8, one f32 scale per (page, position,
head) lane vector riding in per-page scale arrays
``[n_layers, pages, page_size, kv_heads]``.  The write/gather helpers
are shape-generic (they address ``[P, page_size, ...]`` storage by
page), so the same scatter/gather moves codes and scales; the engine
quantizes post-RoPE on write and ``decode_attention`` dequantizes
inside its context strips.  At head_dim 64 that is 68 bytes per cached
vector (64 codes + one f32 scale) vs 128 in bf16 — :meth:`KVCache.bytes`
counts both arrays, so the ~2x capacity-per-HBM-byte claim is
asserted, not assumed.

**Tiered spill (r23).**  LRU eviction *demotes* instead of forgets:
when :meth:`PageAllocator.alloc` runs the free list dry and reclaims
an idle prefix page, the allocator's ``spill_hook`` first copies the
page's contents host-side into a per-engine :class:`HostPagePool`
(tier 1, pinned DRAM), and the pool's own LRU overflow demotes on to a
fleet-shared content-addressed :class:`KVPageStore` (tier 2, the
object store).  Entries are keyed ``(chain_hash, param_version)`` so a
``set_params`` swap invalidates by key mismatch, never by a store
sweep; the spill format defaults to int8 codes + per-vector scales
(:func:`encode_spill_page`), halving resident and wire bytes exactly
as the r20 handoff and r22 DCN paths do.  Promotion is the reverse
walk: admission finds the hash in a lower tier, a fresh HBM page is
allocated, and :func:`install_spill_page` scatters the contents back
between ticks — the same functional ``.at[].set`` as
:func:`import_pages`, zero new executables.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

GARBAGE_PAGE = 0


class HandoffContentMissing(RuntimeError):
    """Typed import failure: a metadata-only (warm) KV handoff reached
    admission but the resident pages it counted on were no longer in
    the prefix index (evicted between the router's digest check and
    the import's admission walk).  Everything the admission touched is
    released before this surfaces — the disagg router treats it as a
    re-prefill-from-prompt signal, never a user-facing error."""

    def __init__(self, rid: int, missing_pages: int):
        super().__init__(
            f"request {rid}: metadata-only KV handoff is missing "
            f"{missing_pages} page(s) no longer resident — re-prefill "
            "from the prompt")
        self.rid = rid
        self.missing_pages = missing_pages

    def __reduce__(self):
        # rebuild from constructor args (the event's error channel can
        # cross the object store on serve streams)
        return (HandoffContentMissing, (self.rid, self.missing_pages))


@dataclasses.dataclass
class KVHandoff:
    """One request's KV-page ownership transfer (disaggregated
    prefill -> decode, r20).

    The payload a prefill replica exports after emitting the first
    sampled token: the cached context's token ids, the chained content
    hashes of its full pages (the importer's skip-transfer key — a
    decode replica already holding a page by hash installs it with a
    refcount bump and never touches the contents), and the raw per-page
    K/V contents host-side — int8 codes + scales ride the same arrays
    when the fleet runs a quantized cache, which is what halves the
    handoff bytes on the wire.  ``k``/``v`` are ``None`` for a
    *metadata-only* (warm) handoff: the router verified every context
    page resident on the importer by digest, so no contents move at
    all.

    Shapes: ``k``/``v`` are ``[n_layers, n_pages, page_size, kv_heads,
    head_dim]`` in the cache's storage dtype; ``k_scale``/``v_scale``
    (int8 caches only) are ``[n_layers, n_pages, page_size, kv_heads]``
    f32.  Page order matches :func:`pages_needed` over ``context``:
    full pages first, then the partial tail (whose positions past
    ``len(context) % page_size`` are garbage the decode attention
    masks, exactly as on the exporter).
    """

    context: List[int]              # token ids whose K/V are cached
    page_size: int
    kv_dtype: str                   # "model" | "int8"
    dtype: str                      # storage dtype name (drift check)
    chain_hashes: List[bytes]       # one per FULL context page
    next_token: int                 # first sampled token (emitted)
    next_logprob: float
    k: Optional[np.ndarray] = None
    v: Optional[np.ndarray] = None
    k_scale: Optional[np.ndarray] = None
    v_scale: Optional[np.ndarray] = None
    # which ABSOLUTE page indices the content arrays carry (None =
    # all of 0..n_pages): a stripped handoff ships only the pages its
    # target does not already hold by chain hash
    present: Optional[List[int]] = None
    # wire form of the request's TraceContext (r24) — the trace rides
    # the payload, so importer-side spans join the exporter's tree
    trace: Optional[dict] = None
    # multi-tenant serving (r25): the adapter the context was prefilled
    # under (None = base).  ``adapter_version`` pins the exact store
    # version — the decode side must attend under the same factors the
    # prefill used, even across a mid-traffic republish; a decode
    # replica lacking it fetches through the AdapterStore on import.
    # The handoff's chain_hashes are already salted by (model_id,
    # version), so prefix digests never alias tenants.
    model_id: Optional[str] = None
    adapter_version: int = 0

    @property
    def n_pages(self) -> int:
        return pages_needed(len(self.context), self.page_size)

    @property
    def n_full_pages(self) -> int:
        return len(self.context) // self.page_size

    @property
    def page_list(self) -> List[int]:
        """Absolute indices of the pages whose contents ride along."""
        if self.k is None:
            return []
        if self.present is None:
            return list(range(self.n_pages))
        return list(self.present)

    @property
    def nbytes(self) -> int:
        """Content bytes on the wire (0 for a metadata-only handoff)."""
        return sum(a.nbytes for a in (self.k, self.v, self.k_scale,
                                      self.v_scale) if a is not None)

    def strip_contents(self) -> "KVHandoff":
        """The metadata-only view (the warm-handoff wire form)."""
        return dataclasses.replace(self, k=None, v=None, k_scale=None,
                                   v_scale=None, present=[])

    def strip_to(self, pages: Sequence[int]) -> "KVHandoff":
        """The wire form carrying only ``pages`` (absolute indices) —
        the partial-residency handoff: pages the target already holds
        by chain hash are dropped from the payload instead of being
        serialized, shipped, and discarded."""
        pages = list(pages)
        if not pages:
            return self.strip_contents()
        have = self.page_list
        sel = [have.index(i) for i in pages]    # raises on a bad strip
        rep = {"present": pages}
        for name in ("k", "v", "k_scale", "v_scale"):
            a = getattr(self, name)
            rep[name] = a[:, sel] if a is not None else None
        return dataclasses.replace(self, **rep)


def handoff_page_bytes(*, n_layers: int, page_size: int, n_heads: int,
                       head_dim: int, itemsize: int,
                       quantized: bool) -> int:
    """Analytic content bytes one handoff page carries — K and V across
    all layers (+ their f32 scale lanes when quantized).  The figure
    ``bench.py --infer --disagg`` checks the measured
    ``serve_handoff_bytes_total`` against: int8 caches move
    ``head_dim + 4`` bytes per cached vector vs ``head_dim * itemsize``
    for the model dtype — ~half of bf16, the disagg wire saving."""
    per_vector = head_dim * itemsize + (4 if quantized else 0)
    return 2 * n_layers * page_size * n_heads * per_vector


def export_pages(cache: "KVCache", pages: Sequence[int]
                 ) -> Dict[str, np.ndarray]:
    """Read ``pages``' K/V contents out of the device cache, host-side:
    ``{"k", "v"[, "k_scale", "v_scale"]}`` stacked ``[L, n_pages, ...]``
    in page order.  One gather per array (a DMA on a real device; the
    in-place object-store put is the on-chip follow-up)."""
    idx = np.asarray(list(pages), np.int32)
    out = {"k": np.asarray(cache.k[:, idx]),
           "v": np.asarray(cache.v[:, idx])}
    if cache.quantized:
        out["k_scale"] = np.asarray(cache.k_scale[:, idx])
        out["v_scale"] = np.asarray(cache.v_scale[:, idx])
    return out


def import_pages(cache: "KVCache", pages: Sequence[int],
                 handoff: "KVHandoff", sel: Sequence[int]) -> None:
    """Write the handoff's pages ``sel`` into ``cache`` at page indices
    ``pages`` (aligned sequences).  Runs between engine ticks on the
    host — a functional ``.at[].set`` that the next compiled step's
    donated state picks up; pages the importer already holds by content
    hash are simply absent from ``sel`` (the skip-transfer path)."""
    if not len(pages):
        return
    idx = np.asarray(list(pages), np.int32)
    sel = np.asarray(list(sel), np.int64)
    cache.k = cache.k.at[:, idx].set(handoff.k[:, sel])
    cache.v = cache.v.at[:, idx].set(handoff.v[:, sel])
    if cache.quantized:
        cache.k_scale = cache.k_scale.at[:, idx].set(
            handoff.k_scale[:, sel])
        cache.v_scale = cache.v_scale.at[:, idx].set(
            handoff.v_scale[:, sel])


SPILL_DTYPES = ("int8", "model")


def _quantize_page(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-vector symmetric int8: ``scale = amax/127`` over the last
    axis, codes rounded-to-nearest — the same block shape the int8
    cache stores, so a spilled page prices identically to a resident
    one (``head_dim + 4`` bytes per cached vector)."""
    x = np.asarray(x, np.float32)
    amax = np.abs(x).max(axis=-1)
    scale = (amax / 127.0).astype(np.float32)
    safe = np.where(scale == 0.0, 1.0, scale)
    codes = np.rint(x / safe[..., None]).clip(-127, 127)
    return codes.astype(np.int8), scale


def encode_spill_page(contents: Dict[str, np.ndarray], *,
                      quantized: bool,
                      spill_dtype: str = "int8") -> Dict[str, object]:
    """One page's host-side spill entry from an :func:`export_pages`
    single-page gather.  int8 caches pass their codes + scales through
    unchanged (already the cheapest exact form); model-dtype caches
    quantize per vector when ``spill_dtype="int8"`` (the default — the
    r11/r22 trick applied to the spill/wire tier) or keep raw bytes
    under ``"model"``."""
    k, v = contents["k"][:, 0], contents["v"][:, 0]
    if quantized:
        return {"fmt": "int8", "k": k, "v": v,
                "k_scale": contents["k_scale"][:, 0],
                "v_scale": contents["v_scale"][:, 0]}
    if spill_dtype == "int8":
        k8, ks = _quantize_page(k)
        v8, vs = _quantize_page(v)
        return {"fmt": "int8", "k": k8, "v": v8,
                "k_scale": ks, "v_scale": vs}
    return {"fmt": "model", "k": np.asarray(k), "v": np.asarray(v)}


def spill_entry_bytes(entry: Dict[str, object]) -> int:
    return sum(a.nbytes for a in entry.values()
               if isinstance(a, np.ndarray))


def spill_entry_matches(cache: "KVCache",
                        entry: Dict[str, object]) -> bool:
    """Geometry guard before an install: a fleet-shared store entry
    written by a different-geometry engine must read as a miss, never
    a shape error mid-admission."""
    want = tuple(cache.k.shape[:1]) + tuple(cache.k.shape[2:])
    return tuple(entry["k"].shape) == want


def install_spill_page(cache: "KVCache", page: int,
                       entry: Dict[str, object]) -> None:
    """Scatter one spilled entry back into device ``page`` — the
    promote leg.  Functional ``.at[:, page].set`` between ticks, like
    :func:`import_pages`: the next compiled step's donated state picks
    it up, so promotion needs zero new executables.  int8 entries feed
    an int8 cache verbatim; a model-dtype cache dequantizes on the
    host first (the int8-budget approximation the r11 parity tests
    bound)."""
    if cache.quantized:
        if entry["fmt"] == "int8":
            k, ks = entry["k"], entry["k_scale"]
            v, vs = entry["v"], entry["v_scale"]
        else:
            k, ks = _quantize_page(entry["k"])
            v, vs = _quantize_page(entry["v"])
        cache.k = cache.k.at[:, page].set(k)
        cache.v = cache.v.at[:, page].set(v)
        cache.k_scale = cache.k_scale.at[:, page].set(ks)
        cache.v_scale = cache.v_scale.at[:, page].set(vs)
        return
    if entry["fmt"] == "int8":
        k = entry["k"].astype(np.float32) * entry["k_scale"][..., None]
        v = entry["v"].astype(np.float32) * entry["v_scale"][..., None]
    else:
        k, v = entry["k"], entry["v"]
    dt = cache.k.dtype
    cache.k = cache.k.at[:, page].set(jnp.asarray(k, dt))
    cache.v = cache.v.at[:, page].set(jnp.asarray(v, dt))


class HostPagePool:
    """Tier 1: the per-engine pinned host-DRAM spill pool.

    An LRU ``(chain_hash, param_version) -> spill entry`` map with a
    hard page capacity.  :meth:`put` is the HBM demote target;
    overflow demotes the oldest entry on to the fleet-shared
    :class:`KVPageStore` (tier 2) when one is attached — through the
    ``kv.spill`` chaos site, so a faulted store leg degrades to
    forgetting the page (a later request re-prefills; nothing hangs).
    :meth:`take` pops — tiers stay exclusive per engine, which is what
    lets the leak audit assert the free/idle/held/host partition
    exactly.
    """

    def __init__(self, capacity_pages: int,
                 store: Optional["KVPageStore"] = None):
        if capacity_pages < 0:
            raise ValueError("host pool capacity must be >= 0")
        self.capacity = capacity_pages
        self.store = store
        self._entries: "collections.OrderedDict[Tuple[bytes, int], Dict]" \
            = collections.OrderedDict()
        self.spills = 0          # entries accepted (HBM -> DRAM)
        self.demotions = 0       # entries pushed on to the store
        self.dropped = 0         # overflow with no store / faulted leg
        self.hits = 0
        self.misses = 0
        self.bytes_spilled = 0
        self.bytes = 0           # current resident bytes

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: Tuple[bytes, int]) -> bool:
        return key in self._entries

    def put(self, key: Tuple[bytes, int],
            entry: Dict[str, object]) -> None:
        from ray_tpu.util import chaos
        if self.capacity == 0:
            self._demote(key, entry, chaos)
            return
        if key in self._entries:
            self._entries.move_to_end(key)
            return
        self._entries[key] = entry
        nb = spill_entry_bytes(entry)
        self.spills += 1
        self.bytes_spilled += nb
        self.bytes += nb
        while len(self._entries) > self.capacity:
            old_key, old = self._entries.popitem(last=False)
            self.bytes -= spill_entry_bytes(old)
            self._demote(old_key, old, chaos)

    def _demote(self, key, entry, chaos) -> None:
        """DRAM -> store leg (or a straight drop without a store)."""
        if self.store is None:
            self.dropped += 1
            return
        try:
            chaos.maybe_fail("kv.spill")
        except chaos.InjectedFault:
            self.dropped += 1       # degrade: re-prefill later
            return
        self.store.put(key, entry)
        self.demotions += 1

    def take(self, key: Tuple[bytes, int]
             ) -> Optional[Dict[str, object]]:
        """Pop an entry for promotion (None on miss)."""
        entry = self._entries.pop(key, None)
        if entry is None:
            self.misses += 1
            return None
        self.hits += 1
        self.bytes -= spill_entry_bytes(entry)
        return entry

    def discard(self, key: Tuple[bytes, int]) -> None:
        """Silently drop an entry that just became HBM-resident again
        (a degraded fetch fell back to prefill and re-registered the
        hash): without this, the hash would sit in two local tiers at
        once and break the exact-partition leak audit.  Not a miss —
        no counter moves."""
        entry = self._entries.pop(key, None)
        if entry is not None:
            self.bytes -= spill_entry_bytes(entry)

    def clear(self) -> int:
        """Drop everything (weight swap: contents are stale)."""
        n = len(self._entries)
        self._entries.clear()
        self.bytes = 0
        return n

    def stats(self) -> Dict[str, int]:
        return {"entries": len(self._entries),
                "capacity": self.capacity, "bytes": self.bytes,
                "spills": self.spills, "demotions": self.demotions,
                "dropped": self.dropped, "hits": self.hits,
                "misses": self.misses,
                "bytes_spilled": self.bytes_spilled}


class KVPageStore:
    """Tier 2: the fleet-shared content-addressed page store.

    ``(chain_hash, param_version) -> spill entry``, shared by every
    replica that holds a reference — the fleet's hit rate compounds
    with each replica added, and a restarted or scaled-from-zero
    replica warms up from here on its first admissions.  Mirrors
    :class:`~ray_tpu.fleet.disagg.HandoffStore`: payloads ride the
    real object store when a session is up (in-process otherwise), a
    put is idempotent by key (content-addressed: same key, same
    bytes), and a :meth:`checkout`/:meth:`checkin` pair brackets every
    fetch so the leak audit can assert no promotion is left in flight.
    Unlike the host pool, :meth:`checkout` does *not* pop — the store
    is shared, and the next replica's miss is this entry's hit.
    ``set_params`` invalidation is by key: a bumped param version
    simply never matches, no sweep required.

    **Byte cap (r24).**  ``RAY_TPU_KV_STORE_CAP`` bounds resident
    bytes: an over-cap put evicts least-recently-*used* entries
    (checkout recency, then insertion order) until the new entry fits.
    An entry mid-checkout is pinned — eviction skips it — so a fetch
    in flight can never lose its payload; if nothing evictable remains
    the cap is allowed to overshoot rather than drop live data.  A
    request whose store pages were evicted simply misses on the walk
    and prefills the suffix — exact greedy continuations, just cold.
    """

    def __init__(self, use_object_store: Optional[bool] = None,
                 capacity_bytes: Optional[int] = None):
        if use_object_store is None:
            try:
                from ray_tpu._private.worker import is_initialized
                use_object_store = is_initialized()
            except Exception:
                use_object_store = False
        if capacity_bytes is None:
            from ray_tpu.inference.config import infer_config
            capacity_bytes = infer_config().store_cap
        self._use_ray = bool(use_object_store)
        self.capacity_bytes = int(capacity_bytes)   # 0 = unbounded
        # insertion/recency-ordered: move_to_end on checkout makes the
        # front the LRU eviction candidate
        self._entries: "collections.OrderedDict[Tuple[bytes, int], object]" \
            = collections.OrderedDict()
        self._bytes: Dict[Tuple[bytes, int], int] = {}
        # per-key checkout pin counts — an entry with fetches in
        # flight is never evicted
        self._pins: Dict[Tuple[bytes, int], int] = {}
        self.puts = 0
        self.dup_puts = 0
        self.gets = 0
        self.misses = 0
        self.bytes_put = 0
        self.evictions = 0
        self.bytes_evicted = 0
        self.in_flight = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: Tuple[bytes, int]) -> bool:
        return key in self._entries

    @property
    def bytes(self) -> int:
        return sum(self._bytes.values())

    def _evict_for(self, incoming: int) -> None:
        if self.capacity_bytes <= 0:
            return
        resident = self.bytes
        victims = [k for k in self._entries
                   if not self._pins.get(k)]
        for key in victims:
            if resident + incoming <= self.capacity_bytes:
                break
            nb = self._bytes.pop(key, 0)
            del self._entries[key]
            resident -= nb
            self.evictions += 1
            self.bytes_evicted += nb

    def put(self, key: Tuple[bytes, int],
            entry: Dict[str, object]) -> None:
        if key in self._entries:        # content-addressed: a no-op
            self.dup_puts += 1
            return
        nb = spill_entry_bytes(entry)
        self._evict_for(nb)
        obj: object = entry
        if self._use_ray:
            import ray_tpu
            obj = ray_tpu.put(entry)
        self._entries[key] = obj
        self._bytes[key] = nb
        self.puts += 1
        self.bytes_put += nb

    def checkout(self, key: Tuple[bytes, int]
                 ) -> Optional[Dict[str, object]]:
        """Fetch an entry without removing it; pair with
        :meth:`checkin` once the install (or its failure path) is
        done.  The entry is pinned against eviction until checked
        back in."""
        obj = self._entries.get(key)
        if obj is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self._pins[key] = self._pins.get(key, 0) + 1
        self.gets += 1
        self.in_flight += 1
        if self._use_ray:
            import ray_tpu
            return ray_tpu.get(obj)
        return obj

    def checkin(self, key: Tuple[bytes, int]) -> None:
        self.in_flight -= 1
        pins = self._pins.get(key, 0) - 1
        if pins > 0:
            self._pins[key] = pins
        else:
            self._pins.pop(key, None)

    def stats(self) -> Dict[str, int]:
        return {"entries": len(self._entries), "bytes": self.bytes,
                "capacity_bytes": self.capacity_bytes,
                "puts": self.puts, "dup_puts": self.dup_puts,
                "gets": self.gets, "misses": self.misses,
                "bytes_put": self.bytes_put,
                "evictions": self.evictions,
                "bytes_evicted": self.bytes_evicted,
                "in_flight": self.in_flight}


class PrefixIndex:
    """Content-addressed index over *full, immutable* KV pages.

    A page is registered under its chained hash
    ``h = H(parent_h, page_tokens)`` — the hash covers the page's own
    tokens *and* (through the parent link) every token before it, so a
    hash hit means the whole prefix up to and including this page is
    byte-identical.  Admission walks a prompt's full pages through
    :meth:`lookup` front-to-back and stops at the first miss; every hit
    is installed into the slot's page-table row with a refcount bump
    and zero prefill compute.

    Pure host metadata: hash -> page and page -> hash maps.  Lifecycle
    (refcounts, the idle-LRU pool, eviction) lives in
    :class:`PageAllocator`, which calls :meth:`forget` when it evicts a
    registered page to reuse its storage.
    """

    ROOT = b""

    def __init__(self):
        self._by_hash: Dict[bytes, int] = {}
        self._by_page: Dict[int, bytes] = {}

    @staticmethod
    def chain(parent: bytes, tokens: Sequence[int]) -> bytes:
        """``H(parent_h, page_tokens)`` — 128-bit blake2b keeps token-
        collision risk negligible while the digest stays dict-cheap."""
        h = hashlib.blake2b(digest_size=16)
        h.update(parent)
        h.update(np.asarray(tokens, np.int64).tobytes())
        return h.digest()

    @classmethod
    def chain_hashes(cls, tokens: Sequence[int],
                     page_size: int, salt: bytes = b"") -> List[bytes]:
        """Chained hashes of every *full* page of ``tokens`` — the one
        walk both the scheduler (registration/hit lookup) and the
        fleet router (affinity matching) must agree on byte-for-byte,
        so it lives here.

        ``salt`` overrides the chain root (r25 multi-tenant serving:
        ``adapters.lora.salt_bytes(model_id, version)``).  Adapter K/V
        differs from base K/V for identical token prefixes, so salted
        chains keep tenants from ever aliasing in the prefix index or
        the tiered store; base traffic keeps the unsalted root, so its
        hashes — and every pre-r25 digest — are unchanged."""
        h = salt or cls.ROOT
        out = []
        for i in range(len(tokens) // page_size):
            h = cls.chain(h, tokens[i * page_size:(i + 1) * page_size])
            out.append(h)
        return out

    @staticmethod
    def hit_eligible(n_tokens: int, page_size: int) -> int:
        """How many leading full pages of an ``n_tokens`` prompt may
        be taken as hits: the page holding the final prompt token is
        excluded even when full — its last token's logits seed the
        first sampled token, so at least one suffix token must always
        prefill."""
        return (n_tokens - 1) // page_size

    def lookup(self, chain_hash: bytes) -> Optional[int]:
        return self._by_hash.get(chain_hash)

    def register(self, chain_hash: bytes, page: int) -> bool:
        """Map ``chain_hash -> page``; refuses (returns False) if either
        side is already registered — first registration wins, so two
        copies of the same content never alias in the index."""
        if chain_hash in self._by_hash or page in self._by_page:
            return False
        self._by_hash[chain_hash] = page
        self._by_page[page] = chain_hash
        return True

    def has(self, page: int) -> bool:
        return page in self._by_page

    def hash_of(self, page: int) -> Optional[bytes]:
        """The chain hash a resident page is registered under — what
        the allocator's spill hook keys the demoted copy by."""
        return self._by_page.get(page)

    def forget(self, page: int) -> None:
        h = self._by_page.pop(page, None)
        if h is not None:
            del self._by_hash[h]

    def clear(self) -> int:
        """Forget every registration (prefix-cache invalidation: the
        cached K/V no longer matches the params after a weight swap).
        Returns how many entries were dropped."""
        n = len(self._by_hash)
        self._by_hash.clear()
        self._by_page.clear()
        return n

    def digest(self) -> frozenset:
        """Snapshot of every registered chain hash — the fleet
        router's prefix-affinity signal: a prompt whose chained page
        hashes appear here would hit this engine's cache.  A frozen
        copy (the router holds it across its own bookkeeping; the
        live dicts keep mutating under admissions), cheap at the
        page-pool sizes a replica runs (hundreds of entries)."""
        return frozenset(self._by_hash)

    def __len__(self) -> int:
        return len(self._by_hash)


class PageAllocator:
    """Refcounted acquire/release allocator over the page pool (page 0
    never handed out).

    Every allocated page carries a refcount: :meth:`alloc` hands out
    pages at refcount 1, a prefix hit :meth:`acquire`\\ s an extra
    reference, and :meth:`release` drops one — storage only becomes
    reusable at refcount 0.  A refcount-0 page *registered in the
    prefix index* is not freed: it parks in an LRU idle pool, its KV
    content intact, so the whole idle cache doubles as prefix storage.
    ``alloc`` takes truly-free pages first and only then evicts idle
    pages oldest-first (unregistering them via ``index.forget``), so
    allocation never fails while idle capacity remains.

    Free/double-free checks are O(1): the free list keeps a companion
    set, and refcounts live in a dict — a retire burst of R requests
    costs O(pages), not the O(R * pages^2) the old ``p in list`` scan
    paid.
    """

    def __init__(self, num_pages: int,
                 index: Optional[PrefixIndex] = None):
        if num_pages < 2:
            raise ValueError(f"need >= 2 pages (1 garbage + 1 usable), "
                             f"got {num_pages}")
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._free_set = set(self._free)
        self._refcount: Dict[int, int] = {}
        # refcount-0 registered pages, insertion order = LRU -> MRU
        self._idle: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self._index = index
        self.evictions = 0
        # r23: called as spill_hook(page, chain_hash) just before a
        # pressure eviction forgets a registered idle page — the
        # engine installs a closure that demotes the page's contents
        # to the host pool.  flush_idle() never spills: a bulk flush
        # means the params changed and the contents are stale.
        self.spill_hook = None

    @property
    def free_count(self) -> int:
        """Pages available to ``alloc``: truly free + evictable idle."""
        return len(self._free) + len(self._idle)

    @property
    def idle_count(self) -> int:
        return len(self._idle)

    def refcount(self, page: int) -> int:
        return self._refcount.get(page, 0)

    def is_idle(self, page: int) -> bool:
        """Registered at refcount 0 (parked in the LRU pool)."""
        return page in self._idle

    def flush_idle(self) -> int:
        """Return every idle page to the free list, forgetting its
        index entry — the bulk invalidation path (a weight swap makes
        all cached K/V stale at once; piecemeal LRU eviction would
        keep serving it until pressure happened to evict)."""
        n = len(self._idle)
        for page in self._idle:
            if self._index is not None:
                self._index.forget(page)
            self._free.append(page)
            self._free_set.add(page)
        self._idle.clear()
        return n

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages at refcount 1, or None (caller keeps the request
        waiting).  Prefers the free list; evicts idle prefix pages
        LRU-first only once it runs dry."""
        if n > self.free_count:
            return None
        pages = []
        for _ in range(n):
            if self._free:
                p = self._free.pop()
                self._free_set.discard(p)
            else:
                p, _ = self._idle.popitem(last=False)   # oldest idle
                self.evictions += 1
                if self._index is not None:
                    if self.spill_hook is not None:
                        h = self._index.hash_of(p)
                        if h is not None:
                            self.spill_hook(p, h)       # demote leg
                    self._index.forget(p)
            self._refcount[p] = 1
            pages.append(p)
        return pages

    def acquire(self, page: int) -> None:
        """Take one more reference on a live or idle page (prefix hit).

        An idle page revives — leaves the LRU pool with its content
        still valid — which is exactly why admission acquires its hits
        *before* allocating fresh pages: the fresh allocation's own
        eviction must not grab a page we are about to share."""
        if page == GARBAGE_PAGE:
            raise ValueError("acquiring the reserved garbage page")
        if page in self._idle:
            del self._idle[page]
            self._refcount[page] = 1
            return
        if page not in self._refcount:
            raise ValueError(f"acquiring unallocated page {page}")
        self._refcount[page] += 1

    def release(self, pages: List[int]) -> None:
        """Drop one reference per page.  At refcount 0 a registered
        page parks in the idle pool (MRU end); an unregistered one
        returns to the free list."""
        for p in pages:
            if p == GARBAGE_PAGE:
                raise ValueError("freeing the reserved garbage page")
            rc = self._refcount.get(p)
            if rc is None:
                raise ValueError(f"double free of page {p}")
            if rc > 1:
                self._refcount[p] = rc - 1
                continue
            del self._refcount[p]
            if self._index is not None and self._index.has(p):
                self._idle[p] = None
            else:
                self._free.append(p)
                self._free_set.add(p)

    # r10-compatible spelling; refcounted release is the real semantics
    free = release


class KVCache:
    """The preallocated paged K/V arrays plus their static geometry.

    ``kv_dtype``: ``"model"`` stores ``dtype`` K/V; ``"int8"`` stores
    int8 codes plus per-(page, position, head) f32 scale arrays.  The
    engine threads :attr:`state` — ``(k, v)`` or
    ``(k, v, k_scale, v_scale)`` — through its donated compiled steps,
    so decode allocates nothing in either mode.
    """

    def __init__(self, *, n_layers: int, num_pages: int, page_size: int,
                 n_heads: int, head_dim: int, dtype,
                 kv_dtype: str = "model"):
        if kv_dtype not in ("model", "int8"):
            raise ValueError(f"unknown kv_dtype {kv_dtype!r}; "
                             "expected 'model' or 'int8'")
        self.num_pages = num_pages
        self.page_size = page_size
        self.kv_dtype = kv_dtype
        self.quantized = kv_dtype == "int8"
        shape = (n_layers, num_pages, page_size, n_heads, head_dim)
        store = jnp.int8 if self.quantized else dtype
        self.k = jnp.zeros(shape, store)
        self.v = jnp.zeros(shape, store)
        if self.quantized:
            # scales start at 0 (fresh garbage dequantizes to zeros),
            # but writes routed to the garbage page overwrite them with
            # real values — its harmlessness rests on decode_attention
            # masking positions >= length, same as the unquantized cache
            self.k_scale = jnp.zeros(shape[:-1], jnp.float32)
            self.v_scale = jnp.zeros(shape[:-1], jnp.float32)

    @property
    def state(self) -> Tuple:
        """The donated device arrays, in step-argument order."""
        if self.quantized:
            return (self.k, self.v, self.k_scale, self.v_scale)
        return (self.k, self.v)

    @state.setter
    def state(self, arrays: Tuple) -> None:
        if self.quantized:
            self.k, self.v, self.k_scale, self.v_scale = arrays
        else:
            self.k, self.v = arrays

    @property
    def bytes(self) -> int:
        """True cache footprint — K/V *and* (when quantized) the scale
        arrays; the r10 figure omitted nothing only because there were
        no scales yet."""
        total = 2 * self.k.size * self.k.dtype.itemsize
        if self.quantized:
            total += 2 * self.k_scale.size * self.k_scale.dtype.itemsize
        return total

    def bytes_per_slot(self, pages_per_slot: int) -> int:
        """HBM bytes one fully-reserved decode slot pins (codes +
        scales across all layers) — the capacity-planning figure the
        telemetry summary and ``bench.py --infer`` report."""
        per_page = (2 * self.k.shape[0] * self.page_size
                    * self.k.shape[3] * self.k.shape[4]
                    * self.k.dtype.itemsize)
        if self.quantized:
            per_page += (2 * self.k.shape[0] * self.page_size
                         * self.k.shape[3]
                         * self.k_scale.dtype.itemsize)
        return pages_per_slot * per_page


def write_prefill(pages, new, page_row, page_size: int):
    """Scatter a prompt's K (or V) into one slot's pages — the cold
    (start-0, whole-bucket) case of :func:`write_prefill_at`.

    pages: [P, page_size, H, D] (one layer); new: [S, H, D] (bucket-
    padded — with ``valid_len = S`` tail positions land in whatever
    ``page_row`` maps them to, the garbage page for unreserved tail
    entries); page_row: [max_pages] int32.  Returns the updated pages
    array."""
    return write_prefill_at(pages, new, page_row, 0, new.shape[0],
                            page_size)


def write_prefill_at(pages, new, page_row, start, valid_len,
                     page_size: int):
    """Scatter a *suffix*'s K (or V) at absolute positions
    ``start .. start+S`` of one slot's pages (the cached-context
    prefill: positions below ``start`` are prefix-cache hits that must
    not be touched).

    pages: [P, page_size, *rest] (one layer); new: [S, *rest] (bucket-
    padded suffix); page_row: [max_pages] int32; start/valid_len:
    traced scalars.  Rows past ``valid_len`` route to the garbage page
    *explicitly* — a suffix bucket can overhang the slot's reserved
    pages (start + bucket > max_pages * page_size), where the cold
    prefill's garbage-padded ``page_row`` tail no longer covers them.
    Returns the updated pages array."""
    S = new.shape[0]
    idx = jnp.arange(S)
    pos = start + idx
    page = jnp.where(
        idx < valid_len,
        page_row[jnp.clip(pos // page_size, 0, page_row.shape[0] - 1)],
        GARBAGE_PAGE)
    return pages.at[page, pos % page_size].set(new)


def write_decode(pages, new, page_table, lengths, page_size: int):
    """Scatter one new token per slot into its page.

    pages: [P, page_size, H, D]; new: [B, H, D]; page_table:
    [B, max_pages] int32; lengths: [B] int32 — the token's absolute
    position (inactive slots point at the garbage page)."""
    B = new.shape[0]
    page = jnp.take_along_axis(page_table,
                               (lengths // page_size)[:, None], 1)[:, 0]
    return pages.at[page, lengths % page_size].set(new)


def gather_pages(pages, page_table):
    """[P, page_size, *rest] x [B, max_pages] -> [B, max_pages*page, *rest].

    The padded per-slot context the decode attention masks by length —
    gather-then-attend (indexing pages *inside* the kernel is the
    natural next step once this path has chip numbers).  Shape-generic
    past the page dims, so K/V codes ([..., H, D]) and their scale
    arrays ([..., H]) ride the same gather."""
    B, max_pages = page_table.shape
    ps = pages.shape[1]
    ctx = pages[page_table]             # [B, max_pages, ps, *rest]
    return ctx.reshape((B, max_pages * ps) + pages.shape[2:])


def pages_needed(tokens: int, page_size: int) -> int:
    return -(-tokens // page_size)


def assert_tail_private(allocator: PageAllocator,
                        index: Optional[PrefixIndex],
                        pages: List[int], first_pos: int,
                        last_pos: int, page_size: int) -> None:
    """Assert the never-write-shared invariant over a slot's write
    window before a speculative verify dispatches: every page that
    positions ``first_pos..last_pos`` land in must be exclusively
    owned (refcount 1) and unregistered — so a rejected draft tail is
    rolled back by simply not advancing the slot's length, and can
    never have clobbered K/V another request shares.

    Provably true by construction (prefix hits and registered pages
    only ever cover FULL prompt/context pages, all strictly below the
    first decode position), so a failure here is a scheduler bug, not
    a traffic pattern — hence an assertion, not an error path."""
    for idx in range(first_pos // page_size,
                     last_pos // page_size + 1):
        page = pages[idx]
        assert allocator.refcount(page) == 1, (
            f"speculative write window touches shared page {page} "
            f"(refcount {allocator.refcount(page)}) — "
            "never-write-shared violated")
        assert index is None or not index.has(page), (
            f"speculative write window touches prefix-registered "
            f"page {page} — never-write-shared violated")
