"""Paged KV cache for continuous-batching decode.

The cache is two preallocated device arrays per model —
``[n_layers, pages, page_size, kv_heads, head_dim]`` K and V — plus a
*host-side* page table: each decode slot owns a row of page indices
covering its reserved context.  Sequences of wildly different lengths
then share one fixed allocation (the vLLM paged-attention idea, here
XLA-functional): admission reserves ``ceil((prompt + max_new) / page)``
pages from a free list, retirement returns them, and the device arrays
never reallocate — the compiled decode step donates them in and gets
them back, so steady-state decode allocates nothing.

Page 0 is reserved as a garbage page: free slots' page-table rows (and
the padded tail of short rows) point at it, so the fixed-shape decode
step can scatter "writes" for inactive slots and prefill can write its
padded bucket tail without corrupting live pages.  Reads of garbage are
masked by per-slot lengths in ``decode_attention``.

Device-side update/gather helpers are plain functional jnp ops (scatter
via ``.at[]``, gather via advanced indexing) so they trace into the
engine's compiled steps; the host-side :class:`PageAllocator` owns the
free list and the leak invariants (``tests/test_inference.py``).

``kv_dtype="int8"`` stores the K/V arrays block-scale-quantized
(``ray_tpu.quant``): codes in int8, one f32 scale per (page, position,
head) lane vector riding in per-page scale arrays
``[n_layers, pages, page_size, kv_heads]``.  The write/gather helpers
are shape-generic (they address ``[P, page_size, ...]`` storage by
page), so the same scatter/gather moves codes and scales; the engine
quantizes post-RoPE on write and ``decode_attention`` dequantizes
inside its context strips.  At head_dim 64 that is 68 bytes per cached
vector (64 codes + one f32 scale) vs 128 in bf16 — :meth:`KVCache.bytes`
counts both arrays, so the ~2x capacity-per-HBM-byte claim is
asserted, not assumed.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

GARBAGE_PAGE = 0


class PageAllocator:
    """Host-side free list over the page pool (page 0 never handed out)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(f"need >= 2 pages (1 garbage + 1 usable), "
                             f"got {num_pages}")
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages, or None (caller keeps the request waiting)."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p == GARBAGE_PAGE:
                raise ValueError("freeing the reserved garbage page")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
            self._free.append(p)


class KVCache:
    """The preallocated paged K/V arrays plus their static geometry.

    ``kv_dtype``: ``"model"`` stores ``dtype`` K/V; ``"int8"`` stores
    int8 codes plus per-(page, position, head) f32 scale arrays.  The
    engine threads :attr:`state` — ``(k, v)`` or
    ``(k, v, k_scale, v_scale)`` — through its donated compiled steps,
    so decode allocates nothing in either mode.
    """

    def __init__(self, *, n_layers: int, num_pages: int, page_size: int,
                 n_heads: int, head_dim: int, dtype,
                 kv_dtype: str = "model"):
        if kv_dtype not in ("model", "int8"):
            raise ValueError(f"unknown kv_dtype {kv_dtype!r}; "
                             "expected 'model' or 'int8'")
        self.num_pages = num_pages
        self.page_size = page_size
        self.kv_dtype = kv_dtype
        self.quantized = kv_dtype == "int8"
        shape = (n_layers, num_pages, page_size, n_heads, head_dim)
        store = jnp.int8 if self.quantized else dtype
        self.k = jnp.zeros(shape, store)
        self.v = jnp.zeros(shape, store)
        if self.quantized:
            # scales start at 0 (fresh garbage dequantizes to zeros),
            # but writes routed to the garbage page overwrite them with
            # real values — its harmlessness rests on decode_attention
            # masking positions >= length, same as the unquantized cache
            self.k_scale = jnp.zeros(shape[:-1], jnp.float32)
            self.v_scale = jnp.zeros(shape[:-1], jnp.float32)

    @property
    def state(self) -> Tuple:
        """The donated device arrays, in step-argument order."""
        if self.quantized:
            return (self.k, self.v, self.k_scale, self.v_scale)
        return (self.k, self.v)

    @state.setter
    def state(self, arrays: Tuple) -> None:
        if self.quantized:
            self.k, self.v, self.k_scale, self.v_scale = arrays
        else:
            self.k, self.v = arrays

    @property
    def bytes(self) -> int:
        """True cache footprint — K/V *and* (when quantized) the scale
        arrays; the r10 figure omitted nothing only because there were
        no scales yet."""
        total = 2 * self.k.size * self.k.dtype.itemsize
        if self.quantized:
            total += 2 * self.k_scale.size * self.k_scale.dtype.itemsize
        return total

    def bytes_per_slot(self, pages_per_slot: int) -> int:
        """HBM bytes one fully-reserved decode slot pins (codes +
        scales across all layers) — the capacity-planning figure the
        telemetry summary and ``bench.py --infer`` report."""
        per_page = (2 * self.k.shape[0] * self.page_size
                    * self.k.shape[3] * self.k.shape[4]
                    * self.k.dtype.itemsize)
        if self.quantized:
            per_page += (2 * self.k.shape[0] * self.page_size
                         * self.k.shape[3]
                         * self.k_scale.dtype.itemsize)
        return pages_per_slot * per_page


def write_prefill(pages, new, page_row, page_size: int):
    """Scatter a prompt's K (or V) into one slot's pages.

    pages: [P, page_size, H, D] (one layer); new: [S, H, D] (bucket-
    padded — tail positions land in whatever ``page_row`` maps them to,
    the garbage page for unreserved tail entries); page_row: [max_pages]
    int32.  Returns the updated pages array."""
    S = new.shape[0]
    pos = jnp.arange(S)
    return pages.at[page_row[pos // page_size], pos % page_size].set(new)


def write_decode(pages, new, page_table, lengths, page_size: int):
    """Scatter one new token per slot into its page.

    pages: [P, page_size, H, D]; new: [B, H, D]; page_table:
    [B, max_pages] int32; lengths: [B] int32 — the token's absolute
    position (inactive slots point at the garbage page)."""
    B = new.shape[0]
    page = jnp.take_along_axis(page_table,
                               (lengths // page_size)[:, None], 1)[:, 0]
    return pages.at[page, lengths % page_size].set(new)


def gather_pages(pages, page_table):
    """[P, page_size, *rest] x [B, max_pages] -> [B, max_pages*page, *rest].

    The padded per-slot context the decode attention masks by length —
    gather-then-attend (indexing pages *inside* the kernel is the
    natural next step once this path has chip numbers).  Shape-generic
    past the page dims, so K/V codes ([..., H, D]) and their scale
    arrays ([..., H]) ride the same gather."""
    B, max_pages = page_table.shape
    ps = pages.shape[1]
    ctx = pages[page_table]             # [B, max_pages, ps, *rest]
    return ctx.reshape((B, max_pages * ps) + pages.shape[2:])


def pages_needed(tokens: int, page_size: int) -> int:
    return -(-tokens // page_size)
