"""Token sampling for the decode loop: greedy / temperature / top-k / top-p.

Per-sequence PRNG: every request owns a key chain
``fold_in(PRNGKey(seed), n_generated)`` derived *inside* the jitted
sampler from its seed and generation count — a sequence's tokens are a
function of (seed, step) only, never of which slot it landed in or who
it was co-batched with.  That property is what makes continuous
batching transparent to callers (asserted by the solo-vs-batched test
in ``tests/test_inference.py``).

All four modes run through one vmapped program (fixed [slots, V] shape,
one compile): temperature scaling, per-row top-k threshold, top-p
nucleus mask computed on the sorted distribution and mapped back by
probability threshold, then a Gumbel argmax; ``temperature <= 0``
selects the plain argmax instead.

The sampler also surfaces the chosen token's **model logprob** —
``log_softmax`` of the *raw* f32 logits at the sampled id, before any
temperature/top-k/top-p shaping.  That is the quantity both consumers
want: serve users get the model's own confidence in the streamed
token, and the RL actors (``ray_tpu.rl``) need ``log pi(a|s)`` under
the distribution the learner differentiates (the policy-gradient step
trains the plain softmax; at ``temperature=1, top_k=0, top_p=1`` the
behavior distribution and the model distribution coincide, so
REINFORCE stays on-policy).  Parity-tested against a teacher-forced
``log_softmax(forward(...))`` recompute in ``tests/test_inference.py``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration.

    ``temperature <= 0`` is greedy (argmax; ``top_k``/``top_p``/``seed``
    are then irrelevant).  ``top_k = 0`` disables the top-k filter;
    ``top_p = 1.0`` disables the nucleus filter.

    ``spec``/``spec_k`` are the per-request speculative-decoding
    overrides (r21): ``None`` defers to the engine defaults
    (``RAY_TPU_INFER_SPEC`` / ``RAY_TPU_INFER_SPEC_K``); ``spec=False``
    pins plain decode for this request, ``spec=True`` opts in with up
    to ``spec_k`` drafted tokens per verify step.  Speculation never
    changes what is sampled — the verify rows run the SAME
    ``fold_in(seed, n_generated)`` key chain as plain decode, so the
    knobs are pure throughput knobs.

    ``model_id`` (r25 multi-tenant serving) selects the LoRA adapter
    this request decodes under (``None`` = the base model).  It rides
    the per-request path like every other knob — serve payload ->
    engine — where it resolves to a slot of the engine's adapter bank,
    loaded through the fleet :class:`~ray_tpu.adapters.AdapterStore`
    on miss; an unknown tenant surfaces the typed
    :class:`~ray_tpu.adapters.AdapterUnavailableError`."""
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    spec: Optional[bool] = None
    spec_k: Optional[int] = None
    model_id: Optional[str] = None


def _sample_one(logits, seed, count, temp, top_k, top_p):
    V = logits.shape[-1]
    l = logits.astype(jnp.float32)
    greedy = jnp.argmax(l, -1).astype(jnp.int32)
    model_logp = jax.nn.log_softmax(l)     # raw-logit distribution
    z = l / jnp.maximum(temp, 1e-6)
    # top-k: threshold at the k-th largest logit (0 = off)
    kth = jnp.sort(z)[::-1][jnp.clip(top_k - 1, 0, V - 1)]
    z = jnp.where((top_k > 0) & (z < kth), -jnp.inf, z)
    # top-p: keep the smallest prefix of the sorted distribution whose
    # mass reaches top_p (the first token always survives), mapped back
    # to vocab order by probability threshold
    probs = jax.nn.softmax(z)
    sp = jnp.sort(probs)[::-1]
    cum = jnp.cumsum(sp)
    keep = (cum - sp) < top_p
    thresh = jnp.min(jnp.where(keep, sp, jnp.inf))
    z = jnp.where(probs >= thresh, z, -jnp.inf)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), count)
    g = -jnp.log(-jnp.log(
        jax.random.uniform(key, (V,), minval=1e-20, maxval=1.0)))
    sampled = jnp.argmax(z + g, -1).astype(jnp.int32)
    tok = jnp.where(temp <= 0.0, greedy, sampled)
    return tok, model_logp[tok]


@functools.partial(jax.jit)
def sample_tokens_logprobs(logits, seeds, counts, temps, top_ks,
                           top_ps):
    """logits [B, V] f32; seeds/counts [B] i32; temps/top_ps [B] f32;
    top_ks [B] i32 -> (token ids [B] i32, chosen-token model logprobs
    [B] f32), row-independent.  The logprob is ``log_softmax`` of the
    raw logits at the chosen id (see module docstring)."""
    return jax.vmap(_sample_one)(logits, seeds, counts, temps, top_ks,
                                 top_ps)


def accept_drafts(sampled, drafts):
    """Vectorized accept/reject for one verify step.

    ``sampled`` [k+1] — the tokens the target model sampled at each
    verify row (row i conditioned on the drafts before it, each under
    its own ``fold_in(seed, count+i)`` key — i.e. EXACTLY the token
    plain decode would have produced at that position); ``drafts`` [k]
    — the self-drafter's proposals.  Draft i is accepted iff every
    earlier draft was and ``sampled[i] == drafts[i]`` (sample-then-
    compare: because the sampled token IS the plain-decode token, a
    full-prefix match means the speculative trajectory and the plain
    trajectory coincide, so acceptance is exact by construction —
    greedy bit-exact, sampled trajectory-exact, no correction
    distribution needed).

    Returns ``(n_accepted, emitted)``: ``emitted`` is
    ``sampled[:n_accepted + 1]`` — the accepted drafts plus one more
    real token (the correction on a reject, the bonus row on a full
    accept)."""
    sampled = np.asarray(sampled)
    drafts = np.asarray(drafts, dtype=sampled.dtype)
    k = drafts.shape[0]
    matches = sampled[:k] == drafts
    n_acc = int(matches.argmin()) if not matches.all() else k
    return n_acc, [int(t) for t in sampled[:n_acc + 1]]


@functools.partial(jax.jit)
def sample_tokens(logits, seeds, counts, temps, top_ks, top_ps):
    """logits [B, V] f32; seeds/counts [B] i32; temps/top_ps [B] f32;
    top_ks [B] i32 -> sampled token ids [B] i32 (row-independent)."""
    tok, _logp = jax.vmap(_sample_one)(logits, seeds, counts, temps,
                                       top_ks, top_ps)
    return tok
