"""Self-drafting for speculative decoding: n-gram copy over the
request's own context.

The drafter is zero-parameter and zero-device-compute: it proposes
continuation tokens by looking the sequence's trailing n-gram up in an
incremental index of the request's OWN tokens (prompt + everything
generated so far) and copying what followed the previous occurrence.
Structured serving traffic — templated prompts, code, JSON, retrieval
contexts quoted back — repeats itself constantly, and a tiny greedy
model loops outright, so prompt-copy drafts hit far above chance
exactly where decode throughput matters.  On a miss the drafter
proposes nothing and the slot falls back to plain decode for the tick;
the engine's verify step makes any proposal *safe* (exact acceptance
sampling — see ``sampling.accept_drafts``), so the drafter needs to be
good, never correct.

Period extension: a trailing n-gram matching at position ``p`` implies
the sequence is locally periodic with period ``d = T - (p + n)``
(position ``q`` repeats ``q - d``), so proposals continue the copy
*through* the end of the real tokens by wrapping modulo ``d`` —
``draft[i] = tokens[p + n + (i % d)]``.  That one rule covers both the
long-range template copy (``d`` large: a verbatim continuation run)
and the tight repetition loop (``d`` small: the loop unrolled to the
full draft budget), without ever proposing from thin air.

Cost: O(max_n) dict updates per generated token and O(max_n) lookups
per proposal — host-side noise next to a decode dispatch.
"""

from __future__ import annotations

from typing import Dict, List, Tuple


class DraftState:
    """Per-request incremental n-gram index + proposer.

    ``index[n]`` maps each n-gram to the start of its latest occurrence
    that *has a continuation*: appending the token at position ``t``
    registers the n-gram ending just before it (``tokens[t-n:t]`` ->
    ``t - n``), so a lookup of the current trailing n-gram can only
    find strictly earlier occurrences — never itself — and the copied
    continuation always exists.  Longest-match-first (``max_n`` down
    to 1) keeps proposals anchored on as much context as available.
    """

    def __init__(self, context: List[int], max_n: int = 3):
        if max_n < 1:
            raise ValueError(f"max_n must be >= 1, got {max_n}")
        self.max_n = max_n
        self.tokens: List[int] = []
        self.index: List[Dict[Tuple[int, ...], int]] = [
            {} for _ in range(max_n + 1)]
        self.extend(context)

    def __len__(self) -> int:
        return len(self.tokens)

    def extend(self, new_tokens) -> None:
        """Append tokens, registering the n-grams each one continues."""
        toks = self.tokens
        for tok in new_tokens:
            t = len(toks)
            for n in range(1, min(self.max_n, t) + 1):
                self.index[n][tuple(toks[t - n:t])] = t - n
            toks.append(int(tok))

    def sync(self, prompt: List[int], generated: List[int]) -> None:
        """Catch the index up to ``prompt + generated`` (the engine
        calls this each planning pass; both lists are append-only, so
        only the unseen generated tail indexes)."""
        have = len(self.tokens) - len(prompt)
        self.extend(generated[have:])

    def propose(self, k: int) -> List[int]:
        """Up to ``k`` drafted continuation tokens ([] = no match —
        the engine runs plain decode for the tick).

        The budget scales with match strength: a ``max_n``-gram match
        spends the full ``k``, and each step down halves it (floor 1).
        Measured on greedy tiny-GPT traffic, a 3-gram match's drafts
        accept ~4x as often as a 1-gram's — spending the whole budget
        on a weak match mostly buys rejected rows, while a 1-token
        draft on a weak match still beats plain decode whenever it
        lands and costs one extra verify row when it doesn't."""
        toks = self.tokens
        T = len(toks)
        for n in range(self.max_n, 0, -1):
            if T < n + 1:       # need the suffix AND an earlier copy
                continue
            p = self.index[n].get(tuple(toks[T - n:]))
            if p is None:
                continue
            d = T - (p + n)     # local period implied by the match
            budget = max(1, k >> (self.max_n - n))
            return [toks[p + n + (i % d)] for i in range(budget)]
        return []
