"""TPU-native continuous-batching inference engine for the GPT family.

The training path compiles one step function and reuses it forever; the
serving path has to survive arbitrary request shapes without paying XLA
compiles on the hot path.  Two mechanisms bound the compile surface
(the arXiv:2011.03641 lesson — steady-state recompiles are the TPU
serving killer):

- **shape buckets**: prompts pad to the smallest configured prefill
  bucket that fits, so prefill compiles at most once per bucket;
- **fixed decode slots**: the decode step is compiled exactly once for
  ``[slots]``-shaped inputs; continuous batching admits/retires
  sequences into those slots (host-side scheduler, Podracer-style
  colocated with the compiled steps) without changing the shape.

**Prefix caching** (``RAY_TPU_INFER_PREFIX``, r12) removes the shared
part of the prefill itself: full prompt pages register in a host-side
content-addressed index, admission installs hits into the page-table
row with refcount bumps, and only the uncached suffix runs through a
*cached-context prefill* executable — suffix self-attention plus
attention over the gathered cached pages, one executable per suffix
bucket with the cached length as a traced scalar, so the compile
surface does not grow with traffic.  Sharing is host metadata plus one
more bucketed step; the compiled prefill/decode steps above never
change shape.

Both step functions are AOT-compiled (``jit(...).lower().compile()``)
into an explicit compile cache with hit/miss counters — an unexpected
shape *raises* instead of silently recompiling, and the zero-recompile
acceptance test asserts on the counters.

**Disaggregated serving seams (r20).**  A prefill-pool engine runs
*first-token-stop* submissions — ``submit(max_new_tokens=1,
hold_pages=True)`` — whose pages survive retirement for
:meth:`export_request` (the KV handoff payload); a decode-pool engine
takes the payload through :meth:`import_submit`, which admits like any
request but installs the pages (resident ones as prefix hits, the
rest written host-side between ticks) and seeds the slot at the
absolute context offset, so the ordinary fixed-slot decode step
continues the sequence — neither seam adds an executable.

**Speculative decoding (r21).**  With ``RAY_TPU_INFER_SPEC`` (or a
per-request ``SamplingParams.spec``) on, each tick plans up to
``spec_k`` self-drafted tokens per slot (``spec.DraftState`` — n-gram
copy over the request's own context, zero parameters) and scores them
all in ONE batched verify forward: the cached-context prefill
executable run in all-rows mode over the suffix ``[last_token,
d1..dk]`` at the slot's current length, compiled once per power-of-two
k-bucket (``verify`` compile counters).  Each verify row is sampled
under the SAME ``fold_in(seed, n_generated)`` key plain decode would
use, so accepting a draft iff the sampled token equals it reproduces
the plain trajectory exactly (greedy bit-exact, sampled
trajectory-exact) — speculation is a pure throughput transform.  A
rejected tail rolls back by simply not advancing the slot's length:
the stale K/V beyond it is length-masked and overwritten by the next
writes, and the write window is slot-private by r12's
never-write-shared invariant (asserted before every dispatch).
Speculating and plain slots co-batch in one tick: the plain decode
step runs with speculating slots' page-table rows masked to the
garbage page, then each speculating slot verifies.

The steps themselves derive from the training model: ``embed`` +
``layer_apply`` with a KV-cache hook threaded through (post-RoPE keys
written to the paged cache, decode attention over the gathered pages
via ``ops/attention.py:decode_attention``), plus the model's own final
norm / tied head so cached decode logits match teacher-forced
``forward`` logits bit-for-bit-modulo-dtype (parity-tested in
``tests/test_inference.py``).  The cache arrays are donated through
every step, so steady-state decode allocates nothing.

Single-device by design for now: ``pallas_call`` has no SPMD rule and
a serving replica owns one chip; sharded multi-chip decode is an open
ROADMAP item.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ray_tpu.adapters import (AdapterRegistry, AdapterStore,
                              AdapterUnavailableError, LoraConfig,
                              lora_config, salt_bytes)
from ray_tpu.adapters import lora as lora_mod
from ray_tpu.inference import kv_cache as kvc
from ray_tpu.inference.config import default_buckets, infer_config
from ray_tpu.inference.sampling import (SamplingParams, accept_drafts,
                                        sample_tokens_logprobs)
from ray_tpu.inference.scheduler import (DeadlineExceededError,
                                         Request, SlotScheduler)
from ray_tpu.inference.spec import DraftState
from ray_tpu.models import gpt as gpt_mod
from ray_tpu.ops.attention import _NEG_INF


class StepEvent(tuple):
    """One ``step()`` event: unpacks and compares as the classic
    ``(rid, token, done)`` 3-tuple, with the sampled token's model
    logprob riding along as an attribute (``ev.logprob``) so logprob
    consumers (the serve stream's ``logprobs`` option, the RL rollout
    actors) don't force a tuple-shape change on every caller.

    ``ev.error`` (default None) is the failure channel: a request
    retired by deadline expiry emits one final event with
    ``done=True``, ``token=-1`` and the typed exception here — the
    serve pump raises it into the request's stream, ``generate()``
    re-raises it, and tuple consumers that ignore the attribute still
    see a clean terminal event."""

    def __new__(cls, rid: int, token: int, done: bool, logprob: float,
                error: Optional[BaseException] = None):
        self = super().__new__(cls, (rid, token, done))
        self.logprob = logprob
        self.error = error
        return self

    def __getnewargs__(self):
        # tuple's default reduce would replay __new__ with the bare
        # 3-tuple; events cross process boundaries here (object store,
        # remote rollout actors), so pickle must carry all five args
        return (self[0], self[1], self[2], self.logprob, self.error)


def _cached_context_attention(q, kctx, vctx, ks, vs, cached_len,
                              scale: Optional[float] = None):
    """Suffix queries over (cached prefix pages + causal suffix self).

    q/ks/vs: [1, S, H, D] — the suffix's (post-RoPE) queries and its
    own full-precision keys/values; kctx/vctx: [1, C, H, D] — the
    slot's gathered page context (only positions < ``cached_len`` are
    the shared prefix; everything else, including the just-written
    suffix copy and garbage pages, is masked out).  One softmax over
    the concatenated [ctx | self] score axis keeps the math identical
    to attention over the full ``cached + suffix`` sequence.  Masked-
    einsum XLA path — runs anywhere, shards nowhere special; the
    Pallas strip-mined variant is the on-chip follow-up.
    """
    B, S, H, D = q.shape
    C = kctx.shape[1]
    if scale is None:
        scale = D ** -0.5
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, kctx,
                    preferred_element_type=jnp.float32) * scale
    ss = jnp.einsum("bqhd,bkhd->bhqk", q, ks,
                    preferred_element_type=jnp.float32) * scale
    ctx_mask = (jnp.arange(C) < cached_len)[None, None, None, :]
    causal = (jnp.arange(S)[:, None]
              >= jnp.arange(S)[None, :])[None, None]
    s = jnp.concatenate([jnp.where(ctx_mask, sc, _NEG_INF),
                         jnp.where(causal, ss, _NEG_INF)], axis=-1)
    m = jnp.max(s, -1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, -1, keepdims=True)                  # [B, H, S, 1]
    o = (jnp.einsum("bhqk,bkhd->bqhd", p[..., :C].astype(vctx.dtype),
                    vctx, preferred_element_type=jnp.float32)
         + jnp.einsum("bhqk,bkhd->bqhd", p[..., C:].astype(vs.dtype),
                      vs, preferred_element_type=jnp.float32))
    l = jnp.swapaxes(l, 1, 2)                          # [B, S, H, 1]
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


class InferenceEngine:
    """Continuous-batching decode engine over one GPT parameter set.

    ``submit()`` enqueues a request and returns its id; ``step()``
    advances the world by one engine tick — admit waiting sequences
    into free slots (one bucketed prefill each), then one batched
    decode for every active slot — and returns ``(rid, token, done)``
    events.  ``generate()`` is the run-to-completion convenience;
    streaming callers (the serve deployment) pump ``step()`` and fan
    events out per request.

    Knobs default to :func:`ray_tpu.inference.config.infer_config`
    (``RAY_TPU_INFER_*``); constructor arguments pin them for tests and
    A/B drivers.  ``debug_logits`` stashes each request's logits rows
    in ``logits_trace[rid]`` for the parity tests.

    ``executable_cache``: params are *call arguments* of the compiled
    steps, not baked constants, so executables only depend on (config,
    geometry).  Callers building several engines over the same model
    shape (re-deploys, A/B drivers, tests) can pass a shared dict to
    compile once per process; the per-engine compile/hit counters still
    count this engine's cache misses/hits.
    """

    def __init__(self, cfg: "gpt_mod.GPTConfig", params, *,
                 slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 buckets: Optional[Tuple[int, ...]] = None,
                 decode_impl: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 prefix: Optional[bool] = None,
                 max_queue: Optional[int] = None,
                 ttft_deadline: Optional[float] = None,
                 deadline: Optional[float] = None,
                 spec: Optional[bool] = None,
                 spec_k: Optional[int] = None,
                 host_pages: Optional[int] = None,
                 store: Union["kvc.KVPageStore", bool, None] = None,
                 spill_dtype: Optional[str] = None,
                 telemetry: Optional[bool] = None,
                 debug_logits: bool = False,
                 executable_cache: Optional[Dict[Any, Any]] = None,
                 lora: Union["LoraConfig", bool, None] = None,
                 adapter_store: Optional["AdapterStore"] = None):
        if cfg.n_experts > 0:
            raise NotImplementedError("MoE decode cache not supported yet")
        icfg = infer_config()
        self.cfg = cfg
        self.params = jax.device_put(params)
        self.slots = slots if slots is not None else icfg.slots
        self.page_size = (page_size if page_size is not None
                          else icfg.page_size)
        self.decode_impl = decode_impl or icfg.decode_impl
        self.kv_dtype = kv_dtype or icfg.kv_dtype
        self.prefix = icfg.prefix if prefix is None else bool(prefix)
        self.max_queue = (icfg.max_queue if max_queue is None
                          else max_queue)
        # default per-request deadlines (0/None = none); per-submit
        # overrides win.  Stored as None-or-positive so the expiry
        # sweep can skip requests without budgets cheaply.
        self.ttft_deadline = (icfg.ttft_deadline if ttft_deadline
                              is None else float(ttft_deadline)) or None
        self.deadline = (icfg.deadline if deadline is None
                         else float(deadline)) or None
        # speculative-decoding defaults; per-request SamplingParams
        # overrides win (resolved once at submit onto Request.spec_k)
        self.spec = icfg.spec if spec is None else bool(spec)
        self.spec_k = icfg.spec_k if spec_k is None else int(spec_k)
        if self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {self.spec_k} "
                             "(check RAY_TPU_INFER_SPEC_K)")
        if self.kv_dtype not in ("model", "int8"):
            raise ValueError(f"unknown kv_dtype {self.kv_dtype!r} "
                             "(check RAY_TPU_KV_DTYPE)")
        if self.slots < 1:
            raise ValueError(f"need >= 1 decode slot, got {self.slots} "
                             "(check RAY_TPU_INFER_SLOTS)")
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got "
                             f"{self.page_size}")
        if self.max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got "
                             f"{self.max_queue} "
                             "(check RAY_TPU_INFER_MAX_QUEUE)")
        self.buckets = tuple(sorted(
            b for b in (buckets or icfg.buckets
                        or default_buckets(cfg.max_seq))
            if b <= cfg.max_seq)) or (cfg.max_seq,)
        max_pages_per_slot = kvc.pages_needed(cfg.max_seq, self.page_size)
        num_pages = num_pages or icfg.pages or (
            self.slots * max_pages_per_slot + 1)
        self.max_pages_per_slot = max_pages_per_slot
        self.scheduler = SlotScheduler(
            slots=self.slots, page_size=self.page_size,
            num_pages=num_pages, max_pages_per_slot=max_pages_per_slot,
            prefix=self.prefix, max_queue=self.max_queue)
        self.cache = kvc.KVCache(
            n_layers=cfg.n_layers, num_pages=num_pages,
            page_size=self.page_size, n_heads=cfg.n_heads,
            head_dim=cfg.head_dim, dtype=cfg.dtype,
            kv_dtype=self.kv_dtype)
        # tiered KV cache (r23): HBM (tier 0, the refcounted pages
        # above) -> per-engine host-DRAM spill pool (tier 1) ->
        # fleet-shared content-addressed page store (tier 2).  ``store``
        # takes a shared KVPageStore (the fleet wiring), True for a
        # private one, None to follow config (a private store when
        # tiering is on and RAY_TPU_KV_STORE allows).  Tiering needs
        # the prefix index — demoted entries are keyed by its chain
        # hashes (+ param version, the set_params invalidation).
        self.host_pages = (icfg.host_pages if host_pages is None
                           else int(host_pages))
        self.spill_dtype = spill_dtype or icfg.spill_dtype
        if self.spill_dtype not in kvc.SPILL_DTYPES:
            raise ValueError(
                f"unknown spill_dtype {self.spill_dtype!r} "
                "(check RAY_TPU_KV_SPILL_DTYPE)")
        if self.host_pages < 0:
            raise ValueError(f"host_pages must be >= 0, got "
                             f"{self.host_pages} "
                             "(check RAY_TPU_KV_HOST_PAGES)")
        if isinstance(store, kvc.KVPageStore):
            self.store: Optional[kvc.KVPageStore] = store
        elif store is True or (store is None and icfg.store
                               and self.host_pages > 0):
            self.store = kvc.KVPageStore()
        else:
            self.store = None
        self.tiered = self.prefix and (self.host_pages > 0
                                       or self.store is not None)
        if self.tiered:
            self.host_pool: Optional[kvc.HostPagePool] = \
                kvc.HostPagePool(self.host_pages, store=self.store)
            self.scheduler.allocator.spill_hook = self._spill_page
            self.scheduler.tier_lookup = self._tier_probe
        else:
            self.host_pool = None
        # per-tier hit/traffic counters (stats()["tiers"] + telemetry)
        self.tier_hits = {"hbm": 0, "dram": 0, "store": 0}
        self.spill_bytes = 0
        self.spill_faults = 0
        self.fetches = 0
        self.fetch_seconds = 0.0
        self.fetch_faults = 0
        # multi-tenant LoRA serving (r25): ``lora`` takes a LoraConfig
        # (explicit geometry), True (env defaults, forced on), or
        # None/False (follow RAY_TPU_LORA).  When on, the engine holds
        # an adapter **bank** — stacked [N, L, in, r]/[N, L, r, out]
        # factors, slot 0 the all-zeros identity — that rides every
        # compiled step as a call argument, plus the per-engine LRU
        # registry mapping model_id -> bank slot.  ``adapter_store``
        # shares the fleet's publication point; lora-on engines
        # default to a private store so direct put()/load flows work.
        if isinstance(lora, LoraConfig):
            self.lora_cfg: Optional[LoraConfig] = lora
        elif lora is True:
            self.lora_cfg = lora_config()
        elif lora is None and lora_config().enabled:
            self.lora_cfg = lora_config()
        else:
            self.lora_cfg = None
        if self.lora_cfg is not None:
            self._lora_targets = lora_mod.effective_targets(
                cfg, self.lora_cfg)
            self.lora_bank = lora_mod.bank_zeros(cfg, self.lora_cfg)
            self.adapters: Optional[AdapterRegistry] = AdapterRegistry(
                self.lora_cfg.cache_slots)
            self.adapter_store: Optional[AdapterStore] = (
                adapter_store if adapter_store is not None
                else AdapterStore())
            lora_key = ("lora", self.lora_cfg.rank,
                        self.lora_cfg.bank_slots, self._lora_targets)
        else:
            self._lora_targets = ()
            self.lora_bank = None
            self.adapters = None
            self.adapter_store = adapter_store
            lora_key = None
        # compile cache: key -> AOT executable; an executable raises on
        # shape drift, so the counters below are honest.  Keys carry
        # the full (cfg, geometry) so a shared cache cannot alias
        # engines of different shapes.
        self._compiled: Dict[Any, Any] = (
            executable_cache if executable_cache is not None else {})
        self._exec_key = (cfg, self.slots, self.page_size, num_pages,
                          max_pages_per_slot, self.decode_impl,
                          self.kv_dtype, lora_key)
        self.compile_counts: Dict[str, int] = {
            "prefill": 0, "prefill_cached": 0, "decode": 0,
            "verify": 0}
        self.hit_counts: Dict[str, int] = {
            "prefill": 0, "prefill_cached": 0, "decode": 0,
            "verify": 0}
        self._requests: Dict[int, Request] = {}
        # speculative-decoding state: per-request drafter indexes
        # (popped at retirement — any terminal path — and bulk-cleared
        # by drain_requests so the reaped-corpse audit stays clean)
        # plus cumulative accept accounting for stats()/telemetry
        self._drafts: Dict[int, DraftState] = {}
        self.spec_proposed = 0
        self.spec_accepted = 0
        # accepted-per-verify histogram: m -> number of verify steps
        # that accepted exactly m drafts
        self.spec_k_hist: Dict[int, int] = {}
        # retired-but-held requests (r20 disagg export seam): pages
        # stay refcounted until export_request/release_held — the leak
        # audit counts them, so an orphaned export is visible
        self._held: Dict[int, Request] = {}
        self.exports = 0
        self.imports = 0
        # r24 tracing: the replica id spans carry (set by
        # fleet.replica.EngineReplica so cross-replica trace trees can
        # attribute work; None = a bare engine)
        self.trace_label: Optional[str] = None
        # store-eviction telemetry is a scrape: the shared store's
        # cumulative counter, deltas reported per tick
        self._store_evictions_seen = (self.store.evictions
                                      if self.store is not None else 0)
        self._next_rid = 0
        self._cancelled: set = set()
        self._lock = threading.Lock()   # submit() vs step() admissions
        # liveness bookkeeping for the resilience watchdog: ``ticks``
        # counts completed step() calls, ``last_tick_ts`` their wall
        # time — a wedged step loop is has_work + neither moving
        self.ticks = 0
        self.last_tick_ts = time.monotonic()
        self.deadline_exceeded = 0
        # versioned params (the RL weight-publication contract): the
        # construction snapshot is version 0 and may alias caller-held
        # arrays, so the first set_params() does not delete it
        self.param_version = 0
        self._owns_params = False
        self.debug_logits = debug_logits
        # rid -> [logits row per generated token], appended in event
        # order (parity tests only; off by default)
        self.logits_trace: Dict[int, List[np.ndarray]] = {}
        from ray_tpu.telemetry.infer import InferTelemetry
        from ray_tpu.telemetry.config import TelemetryConfig
        config = (TelemetryConfig(enabled=True) if telemetry is True
                  else TelemetryConfig(enabled=False)
                  if telemetry is False else None)
        self.telemetry = InferTelemetry(config=config)
        self.telemetry.record_cache_info(
            kv_dtype=self.kv_dtype, cache_bytes=self.cache.bytes,
            kv_bytes_per_slot=self.cache.bytes_per_slot(
                max_pages_per_slot))

    # ---------------------------------------- multi-tenant LoRA (r25)
    def _adapter_release(self, req: Request) -> None:
        """Drop a retiring request's pin on its exact (tenant,
        version) (idempotent: the slot resets so double-retire paths
        can't double-unpin)."""
        if req.adapter_slot > 0 and self.adapters is not None:
            self.adapters.unpin(req.model_id, req.adapter_version)
        req.adapter_slot = 0

    def _check_adapter(self, model_id: str, adapter) -> None:
        """Gate factors against the bank geometry BEFORE the install:
        a tenant publishing a different rank/target set/dims must
        surface as the typed per-request error, never as a jax shape
        error escaping step() and killing the replica for everyone."""
        why = lora_mod.bank_mismatch(self.lora_bank, adapter)
        if why is not None:
            raise AdapterUnavailableError(
                model_id, "published factors do not fit the serving "
                f"bank: {why}")

    def _load_adapter(self, model_id: str,
                      version: Optional[int] = None, *,
                      pin: bool = False) -> Tuple[int, int]:
        """Resolve ``model_id`` to a resident bank slot -> ``(slot,
        installed version)``, loading through the adapter store on a
        miss (``version=None`` tracks the store's latest; a republish
        lands in a *fresh* row, never over a pinned one).  The install
        is an eager ``.at[].set`` over the bank call-arg — compile
        counters never move.  ``pin=True`` pins the resolved (tenant,
        version) under the same lock acquisition that resolves it, so
        the row cannot vanish between resolution and admission.  Fault
        site ``serve.adapter_load`` fires on the load leg only (cache
        hits are unaffected) and surfaces as the typed
        :class:`AdapterUnavailableError`.  Takes ``self._lock``
        internally — callers must NOT hold it: the store checkout can
        block on an object-store fetch, and submit()/cancel()/stats()
        must not stall behind it."""
        reg = self.adapters
        want = version
        if want is None and self.adapter_store is not None:
            want = self.adapter_store.latest_version(model_id)
        with self._lock:
            ent = reg.lookup(model_id, want)
            if ent is not None:
                reg.touch(model_id, ent[1])
                if pin:
                    reg.pin(model_id, ent[1])
                reg.hits += 1
            else:
                reg.misses += 1
        if self.telemetry.enabled:
            self.telemetry.record_adapter_cache(hit=ent is not None)
        if ent is not None:
            return ent
        from ray_tpu.util import chaos
        try:
            chaos.maybe_fail("serve.adapter_load")
        except chaos.InjectedFault as fault:
            raise AdapterUnavailableError(
                model_id, f"load failed: {fault}") from fault
        if self.adapter_store is None:
            raise AdapterUnavailableError(
                model_id, "not resident and the engine has no "
                "adapter store to fetch through")
        t0 = time.monotonic()
        got, adapter, scale = self.adapter_store.checkout(model_id, want)
        try:
            self._check_adapter(model_id, adapter)
            with self._lock:
                slot, _evicted = reg.place(model_id, got)
                self.lora_bank = lora_mod.bank_install(
                    self.lora_bank, slot, adapter, scale=scale)
                if pin:
                    reg.pin(model_id, got)
        finally:
            self.adapter_store.checkin()
        wall = time.monotonic() - t0
        reg.loads += 1
        reg.load_seconds += wall
        if self.telemetry.enabled:
            self.telemetry.record_adapter_load(
                wall, resident=len(reg.resident_ids))
        return slot, got

    def load_adapter(self, model_id: str, adapter, *,
                     scale: float = 1.0, version: int = 1) -> int:
        """Install an adapter's host factors directly into the bank
        (the storeless path: tests, a colocated learner).  Returns the
        bank slot.  Requests referencing ``model_id`` resolve to it
        without touching any store."""
        if self.lora_cfg is None:
            raise AdapterUnavailableError(
                model_id, "engine built without adapter support "
                "(RAY_TPU_LORA / lora=)")
        self._check_adapter(model_id, adapter)
        with self._lock:
            slot, _evicted = self.adapters.place(model_id, int(version))
            self.lora_bank = lora_mod.bank_install(
                self.lora_bank, slot, adapter, scale=scale)
            self.adapters.loads += 1
        return slot

    def _resolve_adapters(self, events: List["StepEvent"]) -> None:
        """Give every waiting multi-tenant request a resident, pinned
        bank slot before admission (step()-only, like every bank
        mutation).  Resolution sets the prefix-chain salt — it MUST
        land before ``_prefix_walk`` first hashes the prompt, so
        adapter K/V never aliases base K/V.  A failed load retires the
        request with the typed error — degraded, never hung.  The
        engine lock is held only around registry/scheduler mutations,
        NOT across the store fetch (``_load_adapter`` takes it at the
        right points itself)."""
        if self.lora_cfg is None:
            return
        with self._lock:
            pending = [r for r in self.scheduler.waiting
                       if r.adapter_slot == -1]
        for req in pending:
            try:
                slot, got = self._load_adapter(
                    req.model_id, req.adapter_version or None,
                    pin=True)
            except AdapterUnavailableError as err:
                with self._lock:
                    if req in self.scheduler.waiting:
                        self.scheduler.waiting.remove(req)
                    self._requests.pop(req.rid, None)
                req.error = err
                req.done = True
                events.append(StepEvent(req.rid, -1, True, 0.0,
                                        error=err))
                continue
            # req fields are read by this (the step) thread only
            req.adapter_slot = slot
            req.adapter_version = got
            req.hash_salt = salt_bytes(req.model_id, got)

    def adapter_digest(self) -> frozenset:
        """Resident tenant model_ids — the router composes this into
        its affinity score beside the prefix digest."""
        if self.adapters is None:
            return frozenset()
        with self._lock:
            return self.adapters.digest()

    # --------------------------------------------------------- requests
    def _resolve_spec_k(self, sampling: SamplingParams) -> int:
        """The request's speculative draft budget (0 = plain decode):
        per-request ``SamplingParams.spec``/``spec_k`` override the
        engine defaults, resolved ONCE here so the hot planning loop
        reads a plain int off the request."""
        on = self.spec if sampling.spec is None else bool(sampling.spec)
        if not on:
            return 0
        k = (self.spec_k if sampling.spec_k is None
             else int(sampling.spec_k))
        if k < 1:
            raise ValueError(f"spec_k must be >= 1, got {k}")
        return k

    def submit(self, prompt, max_new_tokens: int = 16,
               sampling: Optional[SamplingParams] = None,
               eos_token: Optional[int] = None,
               ttft_deadline_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               hold_pages: bool = False,
               trace_ctx=None) -> int:
        """Enqueue one request.  ``hold_pages`` is the disaggregation
        seam (first-token-stop mode is just ``max_new_tokens=1`` with
        it set): when the request retires, its page references survive
        for :meth:`export_request` instead of releasing — the prefill
        side of a prefill/decode split.  ``trace_ctx`` (r24, a
        :class:`~ray_tpu.telemetry.trace.TraceContext`) attaches the
        request to a distributed trace: queue / prefix-walk /
        tier-fetch / prefill / verify spans all hang off its id."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq {self.cfg.max_seq}")
        if len(prompt) > self.buckets[-1]:
            raise ValueError(f"prompt length {len(prompt)} exceeds the "
                             f"largest prefill bucket {self.buckets[-1]}")
        model_id = sampling.model_id if sampling is not None else None
        with self._lock:
            # multi-tenant (r25): validate the tenant up front — a
            # typed submit-time rejection the router can re-route —
            # but defer the actual bank load to step()
            # (``_resolve_adapters``), the only thread that may mutate
            # the bank.  Under the lock so the residency probe can't
            # race a concurrent step()'s eviction/install.
            if model_id:
                if self.lora_cfg is None:
                    raise AdapterUnavailableError(
                        model_id, "engine built without adapter "
                        "support (RAY_TPU_LORA / lora=)")
                if (self.adapters.lookup(model_id) is None
                        and (self.adapter_store is None
                             or model_id not in self.adapter_store)):
                    raise AdapterUnavailableError(
                        model_id, "never published to the adapter "
                        "store")
            rid = self._next_rid
            self._next_rid += 1
            req = Request(rid=rid, prompt=prompt,
                          max_new_tokens=max_new_tokens,
                          sampling=sampling or SamplingParams(),
                          eos_token=eos_token,
                          ttft_deadline_s=(self.ttft_deadline
                                           if ttft_deadline_s is None
                                           else ttft_deadline_s
                                           or None),
                          deadline_s=(self.deadline if deadline_s
                                      is None else deadline_s or None),
                          hold_pages=bool(hold_pages),
                          spec_k=self._resolve_spec_k(
                              sampling or SamplingParams()),
                          trace=trace_ctx,
                          model_id=model_id or None,
                          adapter_slot=-1 if model_id else 0)
            self.scheduler.submit(req)    # validates; may raise —
            self._requests[rid] = req     # register only if accepted
            depth = len(self.scheduler.waiting)
        if self.telemetry.enabled:
            # gauge moves on enqueue too (outside the lock — metric
            # I/O must not serialize against step()'s admissions):
            # under overload there ARE no admissions, so an
            # admission-only gauge would read 0 through the backlog
            self.telemetry.record_queue_depth(depth)
        return rid

    def cancel(self, rid: int) -> None:
        """Retire ``rid`` early (abandoned stream / client disconnect).

        Processed at the start of the next :meth:`step` tick — the only
        place scheduler state mutates besides admission, so a cancel
        can never race a decode that is mid-flight over the slot.  A
        no-op for finished/unknown rids."""
        with self._lock:
            if rid in self._requests:
                self._cancelled.add(rid)

    def drain_requests(self) -> int:
        """Retire every known request NOW, host-side (no device step):
        the teardown path for a replica whose pump died or a supervisor
        replacing a dead rollout engine — nothing may be left holding
        slots/pages/refcounts.  Safe only when no concurrent
        :meth:`step` is running (the callers' situation by
        construction: the stepping thread is gone).  Held exports are
        released too — a reaped corpse must audit clean even when it
        died between first token and handoff.  Returns how many
        requests were retired."""
        with self._lock:
            rids = list(self._requests)
        for rid in rids:
            self.cancel(rid)
        self._process_cancels()
        held = list(self._held)
        for rid in held:
            self.release_held(rid)
        # any in-flight drafter state goes with the requests — a
        # reaped replica must not leak per-request indexes either
        # (stats()["spec"]["drafts"] is the audit's counter)
        self._drafts.clear()
        return len(rids) + len(held)

    # --------------------------------------------- disagg handoff (r20)
    def export_request(self, rid: int) -> "kvc.KVHandoff":
        """Export a retired-but-held request's cached K/V as a
        :class:`~ray_tpu.inference.kv_cache.KVHandoff` and release its
        pages — the prefill side of the prefill/decode split.  The
        payload covers every cached context token (``prompt +
        generated[:-1]``; with first-token-stop submissions that is
        exactly the prompt) plus the next input token the decode side
        seeds its slot with.  Registered full pages park idle in the
        prefix pool on release, so a later handoff of the same prefix
        still prefills nothing here."""
        req = self._held.pop(rid)
        context = list(req.prompt) + list(req.generated[:-1])
        n_pages = kvc.pages_needed(len(context), self.page_size)
        arrays = kvc.export_pages(self.cache, req.pages[:n_pages])
        handoff = kvc.KVHandoff(
            context=context, page_size=self.page_size,
            kv_dtype=self.kv_dtype, dtype=str(self.cache.k.dtype),
            chain_hashes=kvc.PrefixIndex.chain_hashes(
                context, self.page_size, salt=req.hash_salt),
            next_token=int(req.generated[-1]),
            next_logprob=float(req.logprobs[-1]),
            trace=(req.trace.to_wire() if req.trace is not None
                   else None),
            model_id=req.model_id,
            adapter_version=req.adapter_version, **arrays)
        self.scheduler.allocator.release(req.pages)
        req.pages = None
        self.exports += 1
        return handoff

    def release_held(self, rid: int) -> bool:
        """Release a held export without reading it (the failure path:
        the handoff faulted, the stream finished at its first token, or
        the replica is being reaped).  True if ``rid`` was held."""
        req = self._held.pop(rid, None)
        if req is None:
            return False
        self.scheduler.allocator.release(req.pages)
        req.pages = None
        return True

    def import_submit(self, handoff: "kvc.KVHandoff", *,
                      max_new_tokens: int,
                      sampling: Optional[SamplingParams] = None,
                      eos_token: Optional[int] = None,
                      deadline_s: Optional[float] = None) -> int:
        """Enqueue a KV handoff on the decode side of the split.

        The request admits through the ordinary scheduler (slot +
        pages reserved up front; under pressure it waits — queued
        imports ARE the slot-occupancy backlog the decode pool scales
        on), but instead of a prefill the admission installs the
        payload: hit pages (already resident by chain hash) are
        acquired with zero writes, missing pages get the handoff's
        contents, the slot seeds at the absolute context offset, and
        the next decode tick continues the sequence through the one
        compiled decode executable — nothing new ever compiles here.
        ``max_new_tokens`` counts the tokens still to generate (the
        prefill side's first token is already delivered and seeds the
        sampling counts, so sampled continuations stay
        trajectory-exact, not just greedy ones)."""
        if handoff.page_size != self.page_size:
            raise ValueError(
                f"handoff page_size {handoff.page_size} != engine "
                f"page_size {self.page_size} — one fleet geometry")
        if handoff.kv_dtype != self.kv_dtype \
                or (handoff.k is not None
                    and str(handoff.k.dtype) != str(self.cache.k.dtype)):
            raise ValueError(
                f"handoff kv_dtype {handoff.kv_dtype!r} "
                f"(storage {handoff.dtype}) != engine "
                f"{self.kv_dtype!r} ({self.cache.k.dtype}) — the "
                "contents would be reinterpreted, not converted")
        if max_new_tokens < 1:
            raise ValueError("a handoff needs >= 1 token left to "
                             "decode — a finished stream has nothing "
                             "to hand off")
        context = [int(t) for t in handoff.context]
        if len(context) + 1 + max_new_tokens > self.cfg.max_seq:
            raise ValueError(
                f"context ({len(context)}) + remaining tokens "
                f"({1 + max_new_tokens}) exceeds max_seq "
                f"{self.cfg.max_seq}")
        model_id = getattr(handoff, "model_id", None)
        if model_id and self.lora_cfg is None:
            raise AdapterUnavailableError(
                model_id, "decode-side engine built without adapter "
                "support (RAY_TPU_LORA / lora=)")
        trace_ctx = None
        if handoff.trace:
            # the trace context rode the payload across replicas:
            # importer-side spans join the exporter's tree
            from ray_tpu.telemetry import trace as trace_mod
            trace_ctx = trace_mod.TraceContext.from_wire(handoff.trace)
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            # prompt = context, generated seeded at install: the
            # +1 on max_new counts the prefill-side token as this
            # request's first, keeping retire/eos/sampling-count
            # arithmetic identical to a co-located run
            req = Request(rid=rid, prompt=context,
                          max_new_tokens=max_new_tokens + 1,
                          sampling=sampling or SamplingParams(),
                          eos_token=eos_token,
                          ttft_deadline_s=None,
                          deadline_s=(self.deadline if deadline_s
                                      is None else deadline_s or None),
                          chain_hashes=list(handoff.chain_hashes),
                          import_payload=handoff,
                          spec_k=self._resolve_spec_k(
                              sampling or SamplingParams()),
                          trace=trace_ctx,
                          # the importer must decode under the EXACT
                          # factors the prefill used: the version pins
                          # the store fetch across republishes, and the
                          # handoff's chain hashes are already salted
                          model_id=model_id or None,
                          adapter_slot=-1 if model_id else 0,
                          adapter_version=getattr(
                              handoff, "adapter_version", 0),
                          hash_salt=salt_bytes(
                              model_id, getattr(handoff,
                                                "adapter_version", 0)))
            self.scheduler.submit(req)    # validates; may raise
            self._requests[rid] = req
            depth = len(self.scheduler.waiting)
        if self.telemetry.enabled:
            self.telemetry.record_queue_depth(depth)
        return rid

    def _process_cancels(self) -> None:
        with self._lock:
            cancelled, self._cancelled = self._cancelled, set()
            if not cancelled:
                return
            sched = self.scheduler
            for slot, req in list(sched.active.items()):
                if req.rid in cancelled:
                    sched.retire(slot)
                    self._requests.pop(req.rid, None)
                    self._drafts.pop(req.rid, None)
                    self._adapter_release(req)
            for req in [r for r in sched.waiting
                        if r.rid in cancelled]:
                sched.waiting.remove(req)
                req.done = True
                self._requests.pop(req.rid, None)
                self._adapter_release(req)

    def _expire_deadlines(self, events: List["StepEvent"]) -> None:
        """Retire every request past its deadline, at the same safe
        point cancels process (tick start — nothing is mid-flight over
        a slot).  A waiting request can blow either budget (TTFT is
        total-bounded too: ``ttft <= total``); an active request only
        the total one, since admission delivered its first token in
        its admission tick.  Retirement releases everything — slot,
        pages, prefix refcounts — and emits a terminal error event the
        stream surfaces as :class:`DeadlineExceededError`."""
        now = time.monotonic()

        def expiry(req: Request, waiting: bool):
            waited = now - req.submitted_ts
            if waiting and req.ttft_deadline_s is not None \
                    and waited > req.ttft_deadline_s:
                return DeadlineExceededError(req.rid, "ttft",
                                             req.ttft_deadline_s,
                                             waited)
            if req.deadline_s is not None and waited > req.deadline_s:
                return DeadlineExceededError(req.rid, "total",
                                             req.deadline_s, waited)
            return None

        expired: List[Request] = []
        with self._lock:
            sched = self.scheduler
            for req, err in [(r, e) for r in sched.waiting
                             if (e := expiry(r, True)) is not None]:
                sched.waiting.remove(req)
                req.error = err
                req.done = True
                self._requests.pop(req.rid, None)
                self._adapter_release(req)
                expired.append(req)
            for slot, req in list(sched.active.items()):
                err = expiry(req, False)
                if err is not None:
                    sched.retire(slot)
                    req.error = err
                    self._requests.pop(req.rid, None)
                    self._drafts.pop(req.rid, None)
                    self._adapter_release(req)
                    expired.append(req)
        for req in expired:
            self.deadline_exceeded += 1
            if self.telemetry.enabled:
                self.telemetry.record_deadline_exceeded(
                    kind=req.error.kind)
            from ray_tpu.telemetry import trace as trace_mod
            trace_mod.anomaly("deadline", trace=req.trace,
                              rid=req.rid, budget=req.error.kind,
                              budget_s=req.error.budget_s,
                              waited_s=req.error.waited_s,
                              replica=self.trace_label)
            events.append(StepEvent(req.rid, -1, True, 0.0,
                                    error=req.error))

    def set_params(self, params, *, version: Optional[int] = None) -> int:
        """Hot-swap the engine's parameters to a new snapshot.

        ``params`` is a *host-side* pytree (the object-store snapshot
        form the RL learner publishes — numpy leaves); it is copied to
        the device and the **previous** snapshot's buffers are deleted
        eagerly (the donated-buffer swap: steady-state weight
        publication holds one resident copy plus the in-flight
        transfer, never an unbounded trail of dead snapshots waiting
        for GC).  Params are call arguments of the AOT executables, so
        a swap at unchanged shapes/dtypes costs **zero recompiles** —
        the compile counters are the acceptance test.

        Like :meth:`cancel`'s contract, the swap must not race a
        concurrent :meth:`step`: call it between engine ticks (the RL
        rollout actors swap between ``generate()`` calls; a serve
        replica would route it through the pump's executor thread).

        The swap also **invalidates the prefix cache**: registered
        pages hold K/V computed under the old params, and the index
        is keyed by token content alone — without the flush, a
        post-swap request sharing a cached prefix would attend over
        stale context and its logprobs would silently stop matching
        ``forward(new_params)`` (the on-policy contract).

        Returns the new ``param_version`` (monotonic; explicit
        ``version`` pins it — publications carry the learner's own
        counter so actor-side lag is measured in learner versions)."""
        self.scheduler.flush_prefix()
        if self.host_pool is not None:
            # spilled entries hold K/V computed under the old params;
            # drop them rather than demote (the store invalidates by
            # key — the bumped version simply never matches)
            self.host_pool.clear()
        new = jax.device_put(params)
        jax.block_until_ready(new)
        old, self.params = self.params, new
        if self._owns_params:
            new_ids = {id(leaf) for leaf in jax.tree.leaves(new)}
            for leaf in jax.tree.leaves(old):
                if (isinstance(leaf, jax.Array)
                        and id(leaf) not in new_ids
                        and not leaf.is_deleted()):
                    leaf.delete()
        self._owns_params = True
        self.param_version = (self.param_version + 1 if version is None
                              else int(version))
        return self.param_version

    def has_work(self) -> bool:
        with self._lock:
            return self.scheduler.has_work

    def prefix_digest(self) -> frozenset:
        """Registered prefix chain hashes (the ``stats()["prefix"]``
        accounting's underlying index, snapshotted) — the fleet
        router matches a prompt's chained page hashes against this to
        route it to the replica whose cache already holds the prefix."""
        with self._lock:
            return self.scheduler.prefix_digest()

    def stats(self) -> Dict[str, Any]:
        return {
            "compiles": dict(self.compile_counts),
            "hits": dict(self.hit_counts),
            "free_slots": len(self.scheduler.free_slots),
            "free_pages": self.scheduler.allocator.free_count,
            "waiting": len(self.scheduler.waiting),
            "active": len(self.scheduler.active),
            "cache_bytes": self.cache.bytes,
            "kv_dtype": self.kv_dtype,
            "kv_bytes_per_slot": self.cache.bytes_per_slot(
                self.max_pages_per_slot),
            "max_queue": self.max_queue,
            "param_version": self.param_version,
            "prefix": self.scheduler.prefix_stats(),
            "deadline_exceeded": self.deadline_exceeded,
            "ticks": self.ticks,
            # disagg handoff accounting (r20): exports/imports served,
            # and how many retired requests still hold pages for export
            "exports": self.exports,
            "imports": self.imports,
            "held": len(self._held),
            # speculative decoding (r21): cumulative draft accounting
            # plus live drafter-state count (the reaped-corpse audit —
            # a drained engine must read drafts == 0)
            "spec": {
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "accept_rate": (self.spec_accepted / self.spec_proposed
                                if self.spec_proposed else 0.0),
                "k_hist": dict(sorted(self.spec_k_hist.items())),
                "drafts": len(self._drafts),
            },
            # tiered KV cache (r23): per-tier prefix hits plus the
            # demote/promote legs' byte/latency/fault accounting
            "tiers": {
                "enabled": self.tiered,
                "hits": dict(self.tier_hits),
                "spill_dtype": self.spill_dtype,
                "spill_bytes": self.spill_bytes,
                "spill_faults": self.spill_faults,
                "fetches": self.fetches,
                "fetch_seconds": self.fetch_seconds,
                "fetch_faults": self.fetch_faults,
                "host": (self.host_pool.stats()
                         if self.host_pool is not None else None),
                "store": (self.store.stats()
                          if self.store is not None else None),
            },
            # multi-tenant LoRA (r25): registry residency/hit counters
            # plus the shared store's publish/fetch accounting
            "adapters": {
                "enabled": self.lora_cfg is not None,
                **(self.adapters.stats()
                   if self.adapters is not None else {}),
                "store": (self.adapter_store.stats()
                          if self.adapter_store is not None else None),
            },
        }

    # ------------------------------------------------------ engine tick
    def step(self) -> List[StepEvent]:
        """One engine tick -> [(rid, token, done), ...] events (each a
        :class:`StepEvent`: 3-tuple-compatible, ``.logprob`` rides
        along)."""
        events: List[StepEvent] = []
        self._process_cancels()
        self._expire_deadlines(events)
        self._resolve_adapters(events)
        while True:
            with self._lock:
                req = self.scheduler.try_admit()
            if req is None:
                break
            if req.import_payload is not None:
                self._install_import(req, events)
            else:
                if req.n_hit_pages:
                    self.tier_hits["hbm"] += req.n_hit_pages
                    if self.telemetry.enabled:
                        self.telemetry.record_prefix_hits(
                            req.n_hit_pages, tier="hbm")
                if req.tier_plan:
                    self._install_tier_hits(req)
                self._prefill(req, events)
        if self.scheduler.active:
            # speculating slots leave the plain decode batch for this
            # tick (their verify forward IS their decode) and plain
            # slots co-batch as always; an all-speculating tick skips
            # the decode dispatch entirely
            plan = self._plan_speculation()
            if len(plan) < len(self.scheduler.active):
                self._decode(events, skip=set(plan))
            for slot, drafts in plan.items():
                self._verify(slot, drafts, events)
        self.ticks += 1
        self.last_tick_ts = time.monotonic()
        if self.store is not None:
            ev = self.store.evictions
            if ev > self._store_evictions_seen:
                self.telemetry.record_kv_store_evictions(
                    ev - self._store_evictions_seen)
                self._store_evictions_seen = ev
        if self.tiered and self.telemetry.enabled:
            self.telemetry.record_tier_occupancy(
                hbm=len(self.scheduler.prefix_index or ()),
                dram=len(self.host_pool) if self.host_pool else 0,
                store=len(self.store) if self.store else 0)
        return events

    def generate(self, prompts, max_new_tokens: int = 16,
                 sampling: Optional[SamplingParams] = None,
                 eos_token: Optional[int] = None,
                 return_logprobs: bool = False,
                 ttft_deadline_s: Optional[float] = None,
                 deadline_s: Optional[float] = None
                 ) -> Union[List[List[int]],
                            Tuple[List[List[int]], List[List[float]]]]:
        """Run-to-completion over a batch of prompts (ordered results).

        With ``return_logprobs`` the result is ``(token lists, logprob
        lists)`` — each generated token's model logprob, aligned with
        the token lists (the RL rollout form).  A deadline expiry
        raises its :class:`DeadlineExceededError` (streaming callers
        get it per-request via the event's ``error`` instead)."""
        rids = [self.submit(p, max_new_tokens, sampling, eos_token,
                            ttft_deadline_s=ttft_deadline_s,
                            deadline_s=deadline_s)
                for p in prompts]
        out: Dict[int, List[int]] = {r: [] for r in rids}
        lps: Dict[int, List[float]] = {r: [] for r in rids}
        err: Optional[BaseException] = None
        while err is None and self.has_work():
            for ev in self.step():
                rid, tok, _done = ev
                if ev.error is not None:
                    if err is None and rid in out:
                        err = ev.error
                    continue
                if rid in out:          # not a stale leftover rid
                    out[rid].append(tok)
                    lps[rid].append(ev.logprob)
        if err is not None:
            # don't abandon the surviving siblings mid-decode: their
            # slots/pages would stay held and poison the next call
            for r in rids:
                self.cancel(r)
            self._process_cancels()
            raise err
        if return_logprobs:
            return ([out[r] for r in rids], [lps[r] for r in rids])
        return [out[r] for r in rids]

    # ---------------------------------------------------------- prefill
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"no prefill bucket fits length {n}")

    def _prefill(self, req: Request, events) -> None:
        from ray_tpu.util import tracing
        sched = self.scheduler
        slot = req.slot
        plen = len(req.prompt)
        cached = req.cached_tokens
        # the two prefill flavors differ only in executable + scalar
        # args: cold runs the whole prompt, a prefix hit runs just the
        # suffix (attending over the already-cached pages — zero
        # compute for the shared prefix)
        if cached:
            fill = req.prompt[cached:]
            kind, build = "prefill_cached", self._build_prefill_cached
            scalars = (np.int32(cached), np.int32(len(fill)))
        else:
            fill = req.prompt
            kind, build = "prefill", self._build_prefill
            scalars = (np.int32(plen),)
        bucket = self._bucket_for(len(fill))
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :len(fill)] = fill
        t0 = time.monotonic()
        with tracing.span(f"infer/{kind}", rid=req.rid, bucket=bucket,
                          cached=cached):
            if self.lora_cfg is not None:
                aid = np.array([max(req.adapter_slot, 0)], np.int32)
                args = (self.params, self.lora_bank, *self.cache.state,
                        tokens, *scalars, sched.page_table[slot], aid)
            else:
                args = (self.params, *self.cache.state, tokens,
                        *scalars, sched.page_table[slot])
            fn = self._get_compiled((kind, bucket), build, args,
                                    kind=kind)
            logits, *state = fn(*args)
            self.cache.state = tuple(state)
            toks, logps = self._sample_slots(logits, [req])
            tok, logp = toks[0], logps[0]
        # the prompt's K/V are now fully in cache: its full pages are
        # immutable from here on and safe to hand to other requests
        self._register_prefix(req)
        if self.debug_logits:
            self.logits_trace.setdefault(req.rid, []).append(
                np.asarray(logits[0]))
        sched.lengths[slot] = plen
        now = time.monotonic()
        tr = req.trace
        if tr is not None and tr.sampled:
            from ray_tpu.telemetry import trace as trace_mod
            trace_mod.record_span(
                "queue", tr,
                start=trace_mod.epoch_of(req.submitted_ts),
                dur=req.admitted_ts - req.submitted_ts, rid=req.rid,
                replica=self.trace_label)
            trace_mod.record_span(
                "prefill", tr, start=trace_mod.epoch_of(t0),
                dur=now - t0, rid=req.rid, bucket=bucket,
                cached=cached, kind=kind, replica=self.trace_label)
            trace_mod.event("first_token", tr, rid=req.rid,
                            ttft_s=now - req.submitted_ts,
                            replica=self.trace_label)
        if self.telemetry.enabled:
            self.telemetry.record_queue(
                req.admitted_ts - req.submitted_ts,
                depth=len(sched.waiting))
            self.telemetry.record_prefill(now - t0, prompt_tokens=plen,
                                          bucket=bucket,
                                          cached_tokens=cached)
            self.telemetry.record_ttft(
                now - req.submitted_ts, prefix_hit=cached > 0,
                trace_id=tr.trace_id if tr is not None else None)
        self._deliver(req, int(tok), float(logp), events)

    def _install_import(self, req: Request, events) -> None:
        """Seed an admitted import's slot from its handoff payload —
        the decode side of the split, with ZERO compiled steps: hit
        pages are already resident, missing pages are written host-side
        between ticks, and the next batched decode picks the slot up
        like any mid-sequence request (input token = the prefill
        side's sampled token, position = the absolute context
        offset)."""
        handoff = req.import_payload
        sched = self.scheduler
        slot = req.slot
        t0 = time.monotonic()
        n_ctx = len(req.prompt)
        n_pages = kvc.pages_needed(n_ctx, self.page_size)
        present = handoff.page_list
        needed = [i for i in range(req.n_hit_pages, n_pages)]
        missing = [i for i in needed if i not in present]
        if missing:
            # a stripped (warm/partial) handoff whose resident pages
            # were evicted between the router's digest check and this
            # admission: release everything and surface the typed
            # re-prefill signal — never decode over garbage pages
            sched.retire(slot)
            req.error = kvc.HandoffContentMissing(req.rid, len(missing))
            self._requests.pop(req.rid, None)
            self._adapter_release(req)
            events.append(StepEvent(req.rid, -1, True, 0.0,
                                    error=req.error))
            return
        if needed:
            kvc.import_pages(self.cache,
                             [req.pages[i] for i in needed], handoff,
                             [present.index(i) for i in needed])
        # contents are in cache: the imported full pages are immutable
        # from here on and registrable for later handoffs/prompts
        self._register_prefix(req)
        sched.lengths[slot] = n_ctx
        req.generated = [int(handoff.next_token)]
        req.logprobs = [float(handoff.next_logprob)]
        req.cached_tokens = n_ctx
        req.import_payload = None      # drop the content reference
        self.imports += 1
        if req.trace is not None and req.trace.sampled:
            from ray_tpu.telemetry import trace as trace_mod
            trace_mod.record_span(
                "handoff.install", req.trace,
                start=trace_mod.epoch_of(t0),
                dur=time.monotonic() - t0, rid=req.rid,
                pages_written=len(needed), hit_pages=req.n_hit_pages,
                replica=self.trace_label)

    # ------------------------------------------------ tiered cache (r23)
    def _register_prefix(self, req: Request) -> None:
        """Register the request's freshly-written full pages, then drop
        any of those hashes from the host pool: a degraded fetch (fault
        or stale plan) leaves the page to the prefill, and without the
        discard the hash would sit in two local tiers at once — the
        exact-partition invariant the leak audit asserts."""
        self.scheduler.register_prefix(req)
        if self.host_pool is not None and req.chain_hashes:
            for h in req.chain_hashes[req.n_hit_pages:]:
                self.host_pool.discard((h, self.param_version))

    def _tier_probe(self, chain_hash: bytes) -> bool:
        """Does a lower tier hold this hash under the live params?
        The scheduler's ``tier_lookup`` — advisory only: the install
        re-resolves each page and degrades any miss to prefill."""
        key = (chain_hash, self.param_version)
        if self.host_pool is not None and key in self.host_pool:
            return True
        return self.store is not None and key in self.store

    def _spill_page(self, page: int, chain_hash: bytes) -> None:
        """HBM -> host-DRAM demote leg (the allocator's ``spill_hook``,
        fired when pressure evicts a registered idle page).  One
        device->host gather, encoded in the spill dtype, keyed by
        (chain hash, param version).  An injected ``kv.spill`` fault
        degrades to the pre-r23 behavior — the page is simply
        forgotten and a later request re-prefills it."""
        from ray_tpu.util import chaos
        try:
            chaos.maybe_fail("kv.spill")
        except chaos.InjectedFault:
            self.spill_faults += 1
            return
        contents = kvc.export_pages(self.cache, [page])
        entry = kvc.encode_spill_page(contents,
                                      quantized=self.cache.quantized,
                                      spill_dtype=self.spill_dtype)
        nb = kvc.spill_entry_bytes(entry)
        self.spill_bytes += nb
        self.host_pool.put((chain_hash, self.param_version), entry)
        if self.telemetry.enabled:
            self.telemetry.record_kv_spill(nb)

    def _install_tier_hits(self, req: Request) -> None:
        """Promote the admission plan's lower-tier pages into the
        request's freshly-allocated HBM pages, between ticks (the
        ``import_pages`` pattern: functional ``.at[].set``, zero new
        executables).  Pages install front-to-back and the first
        failure — an injected ``kv.fetch`` fault, a plan gone stale
        (demoted past reach or invalidated), a foreign-geometry store
        entry — stops the walk: the remaining pages stay with the
        suffix prefill, so any fault degrades to re-prefill-from-
        prompt with exact continuations, never a hang.  Each installed
        page registers immediately (resident for the next request) and
        counts as a prefix hit via ``note_tier_hits``."""
        from ray_tpu.util import chaos
        sched = self.scheduler
        installed = 0
        for i in range(req.n_hit_pages,
                       req.n_hit_pages + req.tier_plan):
            key = (req.chain_hashes[i], self.param_version)
            t0 = time.monotonic()
            try:
                chaos.maybe_fail("kv.fetch")
            except chaos.InjectedFault:
                self.fetch_faults += 1
                break
            tier = "dram"
            entry = (self.host_pool.take(key)
                     if self.host_pool is not None else None)
            checked_out = False
            if entry is None and self.store is not None:
                entry = self.store.checkout(key)
                checked_out = entry is not None
                tier = "store"
            if entry is None:
                break           # advisory plan went stale: prefill
            try:
                if not kvc.spill_entry_matches(self.cache, entry):
                    break       # foreign geometry reads as a miss
                kvc.install_spill_page(self.cache, req.pages[i],
                                       entry)
            finally:
                if checked_out:
                    self.store.checkin(key)
            if sched.prefix_index is not None:
                sched.prefix_index.register(req.chain_hashes[i],
                                            req.pages[i])
            wall = time.monotonic() - t0
            self.tier_hits[tier] += 1
            self.fetches += 1
            self.fetch_seconds += wall
            if req.trace is not None and req.trace.sampled:
                from ray_tpu.telemetry import trace as trace_mod
                trace_mod.record_span(
                    "tier_fetch", req.trace,
                    start=trace_mod.epoch_of(t0), dur=wall,
                    rid=req.rid, tier=tier, page_index=i,
                    replica=self.trace_label)
            if self.telemetry.enabled:
                self.telemetry.record_kv_fetch(wall, tier=tier)
                self.telemetry.record_prefix_hits(1, tier=tier)
            installed += 1
        req.tier_plan = 0
        sched.note_tier_hits(req, installed)

    def leak_free(self) -> bool:
        """Tier-inventory audit: the usable HBM pages partition exactly
        into free / idle / held, the host pool respects its capacity
        and never holds a hash that is also resident (a demoted entry
        is in exactly one local tier), and no store fetch is left in
        flight.  The fleet replicas' audits call through here."""
        alloc = self.scheduler.allocator
        free = set(alloc._free)
        idle = set(alloc._idle)
        held = set(alloc._refcount)
        usable = set(range(1, alloc.num_pages))
        if (free | idle | held != usable or (free & idle)
                or (free & held) or (idle & held)):
            return False
        if len(alloc._free) != len(alloc._free_set):
            return False
        if self.host_pool is not None:
            if len(self.host_pool) > self.host_pool.capacity:
                return False
            if self.scheduler.prefix_index is not None:
                resident = {(h, self.param_version) for h in
                            self.scheduler.prefix_index.digest()}
                if resident & set(self.host_pool._entries):
                    return False
        if self.store is not None and self.store.in_flight != 0:
            return False
        if self.adapters is not None:
            # every live pin must belong to a live multi-tenant
            # request, and store checkouts must have been checked in
            live = sum(1 for r in self._requests.values()
                       if r.adapter_slot > 0)
            if self.adapters.pinned_total != live:
                return False
        if (self.adapter_store is not None
                and self.adapter_store.in_flight != 0):
            return False
        return True

    # ----------------------------------------------------------- decode
    def _decode(self, events, skip: Optional[Set[int]] = None) -> None:
        from ray_tpu.util import chaos, tracing

        # fault site BEFORE any cache/scheduler mutation and before the
        # donated executable dispatches: an injected decode failure
        # leaves the engine state consistent (slots/pages still held,
        # cache arrays live), so supervisors can cancel/drain cleanly
        chaos.maybe_fail("infer.decode")
        skip = skip or set()
        sched = self.scheduler
        tokens = np.zeros((self.slots,), np.int32)
        reqs: List[Optional[Request]] = [None] * self.slots
        for slot, req in sched.active.items():
            if slot in skip:
                continue
            tokens[slot] = req.generated[-1]
            reqs[slot] = req
        active = [r for r in reqs if r is not None]
        page_table = sched.page_table
        if skip:
            # speculating slots ride this dispatch as dead rows (the
            # decode step's shape is fixed): their page rows mask to
            # the garbage page so the batched K/V write cannot touch
            # the positions their verify forward is about to fill, and
            # their sampled outputs are never delivered
            page_table = page_table.copy()
            page_table[list(skip), :] = kvc.GARBAGE_PAGE
        t0 = time.monotonic()
        with tracing.span("infer/decode", active=len(active)):
            if self.lora_cfg is not None:
                # per-slot adapter ids: co-batched tenants share this
                # one tick (the bank gather routes each row through its
                # own A/B factors; dead/base rows ride slot 0 identity)
                aids = np.zeros((self.slots,), np.int32)
                for slot, req in sched.active.items():
                    if slot not in skip and req.adapter_slot > 0:
                        aids[slot] = req.adapter_slot
                args = (self.params, self.lora_bank, *self.cache.state,
                        tokens, sched.lengths, page_table, aids)
            else:
                args = (self.params, *self.cache.state, tokens,
                        sched.lengths, page_table)
            fn = self._get_compiled(("decode",), self._build_decode,
                                    args, kind="decode")
            logits, *state = fn(*args)
            self.cache.state = tuple(state)
            sampled, logps = self._sample_slots(logits, reqs)
        wall = time.monotonic() - t0
        traced = [r.trace.trace_id for r in active
                  if r.trace is not None and r.trace.sampled]
        if traced:
            # ONE coalesced span per tick (trace_id=None: a global
            # span), carrying the sampled trace ids it served — a span
            # per (tick, request) would swamp the ring at decode rate
            from ray_tpu.telemetry import trace as trace_mod
            trace_mod.record_span(
                "decode_tick", None, start=trace_mod.epoch_of(t0),
                dur=wall, active=len(active), trace_ids=traced,
                replica=self.trace_label)
        if self.telemetry.enabled:
            self.telemetry.record_decode(wall, active=len(active))
        if self.debug_logits:
            host_logits = np.asarray(logits)
        for slot in list(sched.active):
            if slot in skip:
                continue
            req = sched.active[slot]
            sched.lengths[slot] += 1     # the input token is now cached
            if self.debug_logits:
                self.logits_trace.setdefault(req.rid, []).append(
                    host_logits[slot])
            self._deliver(req, int(sampled[slot]),
                          float(logps[slot]), events)

    # ---------------------------------------------- speculation (r21)
    def _plan_speculation(self) -> Dict[int, List[int]]:
        """slot -> drafted tokens for this tick (empty dict = plain
        decode for everyone).  A slot speculates when its request
        opted in (``spec_k > 0``), has more than one token left to
        generate, and its drafter finds a context match; the draft
        budget is clipped to the remaining token budget so the verify
        write window provably stays inside the pages reserved at
        admission (highest written position = ``len(prompt) +
        max_new_tokens - 1``, the last reserved token)."""
        plan: Dict[int, List[int]] = {}
        for slot, req in self.scheduler.active.items():
            if req.spec_k <= 0:
                continue
            remaining = req.max_new_tokens - len(req.generated)
            k = min(req.spec_k, remaining)
            if k < 1:
                continue
            ds = self._drafts.get(req.rid)
            if ds is None:
                ds = DraftState(req.prompt)
                self._drafts[req.rid] = ds
            ds.sync(req.prompt, req.generated)
            drafts = ds.propose(k)
            if drafts:
                plan[slot] = drafts
        return plan

    @staticmethod
    def _verify_bucket(n_drafts: int) -> int:
        """Power-of-two draft-capacity bucket: one verify executable
        per bucket serves every draft length up to it (suffix_len is a
        traced scalar), so mixed-k traffic compiles O(log max_k)
        executables, then zero."""
        kb = 1
        while kb < n_drafts:
            kb *= 2
        return kb

    def _verify(self, slot: int, drafts: List[int], events) -> None:
        """Score ``[last_token, d1..dk]`` in ONE cached-context
        forward (all-rows mode), sample every row under the request's
        own ``fold_in`` key chain, and emit the accepted prefix plus
        one more real token (``sampling.accept_drafts``).  The slot's
        length advances only over emitted tokens — the rejected tail's
        K/V stays behind the length mask and is overwritten by the
        next writes, which IS the rollback (the write window is
        slot-private; asserted below)."""
        from ray_tpu.util import tracing
        sched = self.scheduler
        req = sched.active[slot]
        L = int(sched.lengths[slot])
        n_drafts = len(drafts)
        kb = self._verify_bucket(n_drafts)
        # never-write-shared: the verify writes positions L..L+k of
        # this slot — all strictly past every shared/registered page
        # by construction (full prompt/context pages end before the
        # first decode position), so rollback can never corrupt a
        # page another request reads
        kvc.assert_tail_private(
            sched.allocator, sched.prefix_index, req.pages,
            L, L + n_drafts, self.page_size)
        tokens = np.zeros((1, kb + 1), np.int32)
        tokens[0, 0] = req.generated[-1]
        tokens[0, 1:1 + n_drafts] = drafts
        t0 = time.monotonic()
        with tracing.span("infer/verify", rid=req.rid, k=n_drafts):
            if self.lora_cfg is not None:
                aid = np.array([max(req.adapter_slot, 0)], np.int32)
                args = (self.params, self.lora_bank, *self.cache.state,
                        tokens, np.int32(L), np.int32(n_drafts + 1),
                        sched.page_table[slot], aid)
            else:
                args = (self.params, *self.cache.state, tokens,
                        np.int32(L), np.int32(n_drafts + 1),
                        sched.page_table[slot])
            fn = self._get_compiled(
                ("verify", kb),
                functools.partial(self._build_prefill_cached,
                                  all_rows=True),
                args, kind="verify")
            logits, *state = fn(*args)
            self.cache.state = tuple(state)
            # every row samples under the key plain decode would use
            # at that position: row i's token lands when generated has
            # len(generated) + i tokens, so counts advance from there
            c = len(req.generated)
            n_rows = kb + 1
            seeds = np.full((n_rows,), req.sampling.seed, np.int32)
            counts = c + np.arange(n_rows, dtype=np.int32)
            temps = np.full((n_rows,), req.sampling.temperature,
                            np.float32)
            top_ks = np.full((n_rows,), req.sampling.top_k, np.int32)
            top_ps = np.full((n_rows,), req.sampling.top_p, np.float32)
            toks, logps = sample_tokens_logprobs(
                logits[0], seeds, counts, temps, top_ks, top_ps)
            toks, logps = np.asarray(toks), np.asarray(logps)
        wall = time.monotonic() - t0
        m, emitted = accept_drafts(toks[:n_drafts + 1], drafts)
        self.spec_proposed += n_drafts
        self.spec_accepted += m
        self.spec_k_hist[m] = self.spec_k_hist.get(m, 0) + 1
        if req.trace is not None and req.trace.sampled:
            from ray_tpu.telemetry import trace as trace_mod
            trace_mod.record_span(
                "verify", req.trace, start=trace_mod.epoch_of(t0),
                dur=wall, rid=req.rid, proposed=n_drafts, accepted=m,
                replica=self.trace_label)
        if self.debug_logits:
            host_logits = np.asarray(logits[0])
        delivered = 0
        for i, tok in enumerate(emitted):
            # the input token of row i (last_token or draft i) is now
            # cached at position L + i; advancing BEFORE delivery
            # keeps the decode-step length semantics, and a retire
            # inside the block (EOS / max_new) resets the slot anyway
            sched.lengths[slot] = L + i + 1
            if self.debug_logits:
                self.logits_trace.setdefault(req.rid, []).append(
                    host_logits[i])
            self._deliver(req, int(tok), float(logps[i]), events)
            delivered += 1
            if req.done:
                break
        if self.telemetry.enabled:
            self.telemetry.record_verify(
                wall, proposed=n_drafts, accepted=m,
                emitted=delivered)

    def _deliver(self, req: Request, tok: int, logp: float,
                 events) -> None:
        req.generated.append(tok)
        req.logprobs.append(logp)
        done = (len(req.generated) >= req.max_new_tokens
                or (req.eos_token is not None and tok == req.eos_token))
        if done:
            if req.hold_pages:
                # disagg export seam: the slot frees but the pages stay
                # refcounted for export_request/release_held
                self.scheduler.retire_hold(req.slot)
                self._held[req.rid] = req
            else:
                self.scheduler.retire(req.slot)
            # the adapter unpins with the slot either way: a held
            # export only needs pages — the importer re-pins the
            # adapter on its own replica through the handoff metadata
            self._adapter_release(req)
            if self.telemetry.enabled:
                self.telemetry.record_request_done()
            self._drafts.pop(req.rid, None)
            if not self.debug_logits:
                # a serve replica lives for the deployment's lifetime:
                # finished requests must not accumulate (debug engines
                # keep them so parity tests can read trajectories)
                self._requests.pop(req.rid, None)
        events.append(StepEvent(req.rid, tok, done, logp))

    # --------------------------------------------------------- sampling
    def _sample_slots(self, logits, reqs: List[Optional[Request]]
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Sample one token per logits row — the full [slots, V] decode
        batch (None rows are inactive, result discarded) or a prefill's
        single [1, V] row.  Returns ``(tokens, model logprobs)``."""
        null = SamplingParams()
        seeds = np.array([(r.sampling.seed if r else 0) for r in reqs],
                         np.int32)
        counts = np.array([(len(r.generated) if r else 0) for r in reqs],
                          np.int32)
        temps = np.array(
            [(r.sampling.temperature if r else null.temperature)
             for r in reqs], np.float32)
        top_ks = np.array([(r.sampling.top_k if r else 0) for r in reqs],
                          np.int32)
        top_ps = np.array([(r.sampling.top_p if r else 1.0)
                           for r in reqs], np.float32)
        toks, logps = sample_tokens_logprobs(logits, seeds, counts,
                                             temps, top_ks, top_ps)
        return np.asarray(toks), np.asarray(logps)

    # ---------------------------------------------------- compile cache
    def _get_compiled(self, key, build_fn, example_args, *, kind: str):
        key = self._exec_key + key
        fn = self._compiled.get(key)
        if fn is not None:
            self.hit_counts[kind] += 1
            return fn
        self.compile_counts[kind] += 1
        jitted = build_fn()
        fn = jitted.lower(*example_args).compile()
        self._compiled[key] = fn
        return fn

    # ------------------------------------------------------- step fns --
    def _embed(self, params, tokens, positions):
        """tokens [B, S], positions [S] or [B, S] -> hidden [B, S, d].

        ``embed_tokens`` assumes positions 0..S-1 for learned tables;
        prefill/decode index the table by absolute position instead."""
        cfg = self.cfg
        x = params["embed"].astype(cfg.dtype)[tokens]
        if cfg.pos == "learned":
            pe = params["pos_embed"].astype(cfg.dtype)[positions]
            x = x + (pe if positions.ndim == 2 else pe[None])
        return x

    def _layer_scan(self, params, x, caches, positions, attn_hook,
                    lora_bank=None, lora_ids=None):
        """Run the layer stack with per-layer cache slices in the scan
        carry (dynamic-slice in / dynamic-update out, the donation-
        friendly pattern) -> (final normed hidden, caches).

        ``caches`` is the cache's state tuple of stacked ``[L, ...]``
        arrays — ``(k, v)`` or, quantized, ``(k, v, k_scale,
        v_scale)``; the per-layer slice tuple is opaque to
        ``layer_apply`` and round-trips through ``attn_hook``.

        ``lora_bank``/``lora_ids`` (r25 multi-tenant): bank factors are
        stacked ``[N, L, ...]`` — layer axis 1 — sliced per scan step;
        ``lora_ids`` [B] routes each batch row through its tenant's
        slot (slot 0 is the all-zeros identity, so base rows cost one
        fused-zero gather, never a branch)."""
        cfg = self.cfg

        def body(carry, i):
            x, caches = carry
            lp = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, i, 0,
                                                   keepdims=False),
                params["layers"])
            lora = None
            if lora_bank is not None:
                lora = {k: lax.dynamic_index_in_dim(v, i, 1,
                                                    keepdims=False)
                        for k, v in lora_bank.items() if k != "scale"}
                lora["scale"] = lora_bank["scale"]
                lora["ids"] = lora_ids
            layer_cache = tuple(
                lax.dynamic_index_in_dim(c, i, 0, keepdims=False)
                for c in caches)
            x, _aux, layer_cache = gpt_mod.layer_apply(
                lp, x, cfg, positions=positions, attn_fn=attn_hook,
                cache=layer_cache, lora=lora)
            caches = tuple(
                lax.dynamic_update_index_in_dim(c, nc, i, 0)
                for c, nc in zip(caches, layer_cache))
            return (x, caches), None

        (x, caches), _ = lax.scan(
            body, (x, caches), jnp.arange(cfg.n_layers))
        x = gpt_mod._norm(x, params["ln_f"], cfg.norm,
                          bias=params.get("ln_f_b"),
                          eps=gpt_mod.norm_eps(cfg))
        return x, caches

    def _quantize_kv(self, kv):
        """[..., H, D] post-RoPE K or V -> (int8 codes, [..., H] f32
        scales): one scale per head_dim lane vector (deterministic
        rounding — cache entries are weights-like, read many times)."""
        from ray_tpu.quant import quantize_block
        q, s = quantize_block(kv, block=self.cfg.head_dim, axis=-1)
        return q, s[..., 0]

    def _build_prefill(self):
        cfg = self.cfg
        page_size = self.page_size
        quantized = self.kv_dtype == "int8"

        lora_on = self.lora_cfg is not None

        def prefill(params, *args):
            """(params, [lora_bank,] *cache_state, tokens [1,
            S_bucket], length scalar (valid prefix), page_row
            [max_pages][, adapter_ids [1]]) -> (last-token logits
            [1, V] f32, *cache_state)."""
            bank = aids = None
            if lora_on:
                bank, *args = args
                *args, aids = args
            *cache_state, tokens, length, page_row = args
            S = tokens.shape[1]
            positions = jnp.arange(S)

            def attn_hook(q, k, v, cache):
                if quantized:
                    ck, cv, cks, cvs = cache
                    kq, ks = self._quantize_kv(k[0])
                    vq, vs = self._quantize_kv(v[0])
                    ck = kvc.write_prefill(ck, kq, page_row, page_size)
                    cv = kvc.write_prefill(cv, vq, page_row, page_size)
                    cks = kvc.write_prefill(cks, ks, page_row,
                                            page_size)
                    cvs = kvc.write_prefill(cvs, vs, page_row,
                                            page_size)
                    new_cache = (ck, cv, cks, cvs)
                else:
                    ck, cv = cache
                    ck = kvc.write_prefill(ck, k[0], page_row,
                                           page_size)
                    cv = kvc.write_prefill(cv, v[0], page_row,
                                           page_size)
                    new_cache = (ck, cv)
                # attention reads the full-precision prompt K/V (the
                # prompt IS the whole context); quantization only
                # affects what later decode steps read back
                o = self._prefill_attention(q, k, v)
                return o, new_cache

            x = self._embed(params, tokens, positions)
            x, cache_state = self._layer_scan(params, x,
                                              tuple(cache_state),
                                              positions, attn_hook,
                                              lora_bank=bank,
                                              lora_ids=aids)
            h = jnp.take(x[0], length - 1, axis=0)[None, None]  # [1,1,d]
            logits = jnp.einsum("bsd,dv->bsv", h,
                                gpt_mod.lm_head(params, cfg))
            return (logits[:, 0].astype(jnp.float32),) + cache_state

        n_state = len(self.cache.state)
        first = 2 if lora_on else 1      # cache state shifts past bank
        return jax.jit(prefill,
                       donate_argnums=tuple(range(first,
                                                  first + n_state)))

    def _prefill_attention(self, q, k, v):
        """Causal self-attention over the bucket (no cache read — the
        prompt is the whole context).  Flash kernel on a real TPU,
        einsum elsewhere (interpret-mode Pallas is only paid for in the
        dedicated kernel tests, not every engine test)."""
        if jax.default_backend() == "tpu":
            from ray_tpu.ops.attention import flash_attention
            return flash_attention(q, k, v, causal=True)
        from ray_tpu.parallel.ring_attention import local_attention
        return local_attention(q, k, v, causal=True)

    def _build_prefill_cached(self, all_rows: bool = False):
        """Suffix-only prefill over a prefix-cached context.

        The prompt's first ``cached_len`` tokens are already in the
        slot's pages (prefix-index hits: written by an earlier request
        with an identical prefix — byte-identical content, and for
        int8 caches bit-identical codes because cache writes round
        deterministically).  Only the suffix runs through the model:
        its queries attend over the gathered cached pages (length-
        masked) *plus* causally over the suffix itself, merged in one
        softmax — the masked-einsum XLA formulation (a Pallas variant
        is an on-chip follow-up; see docs/PERF.md r12).

        ``cached_len``/``suffix_len`` are traced scalars, so one
        executable per *suffix bucket* serves every cached length —
        the zero-steady-state-recompile counters still hold.

        ``all_rows=True`` is the speculative **verify** flavor (r21):
        the suffix is ``[last_token, d1..dk]`` and the caller needs
        the logits at EVERY suffix position (row i scores the token
        after draft i), so the head runs over the whole suffix and
        the executable returns ``[1, S_bucket, V]`` instead of the
        last valid row.  Same attention, same cache writes — the
        verify step is literally the cached-context prefill run one
        slot at a time.
        """
        cfg = self.cfg
        page_size = self.page_size
        quantized = self.kv_dtype == "int8"
        lora_on = self.lora_cfg is not None

        def prefill_cached(params, *args):
            """(params, [lora_bank,] *cache_state, tokens [1, S_bucket]
            (suffix, padded), cached_len scalar (prefix tokens already
            in cache), suffix_len scalar (valid suffix), page_row
            [max_pages][, adapter_ids [1]]) -> (last-suffix-token
            logits [1, V] f32, *cache_state)."""
            bank = aids = None
            if lora_on:
                bank, *args = args
                *args, aids = args
            *cache_state, tokens, cached_len, suffix_len, page_row = args
            S = tokens.shape[1]
            positions = cached_len + jnp.arange(S)   # absolute

            def attn_hook(q, k, v, cache):
                row = page_row[None]                 # [1, max_pages]
                if quantized:
                    ck, cv, cks, cvs = cache
                    kq, ks_ = self._quantize_kv(k[0])
                    vq, vs_ = self._quantize_kv(v[0])
                    ck = kvc.write_prefill_at(ck, kq, page_row,
                                              cached_len, suffix_len,
                                              page_size)
                    cv = kvc.write_prefill_at(cv, vq, page_row,
                                              cached_len, suffix_len,
                                              page_size)
                    cks = kvc.write_prefill_at(cks, ks_, page_row,
                                               cached_len, suffix_len,
                                               page_size)
                    cvs = kvc.write_prefill_at(cvs, vs_, page_row,
                                               cached_len, suffix_len,
                                               page_size)
                    new_cache = (ck, cv, cks, cvs)
                    kctx = kvc.gather_pages(ck, row)
                    vctx = kvc.gather_pages(cv, row)
                    ksc = kvc.gather_pages(cks, row)
                    vsc = kvc.gather_pages(cvs, row)
                    kctx = (kctx.astype(jnp.float32)
                            * ksc[..., None]).astype(q.dtype)
                    vctx = (vctx.astype(jnp.float32)
                            * vsc[..., None]).astype(q.dtype)
                else:
                    ck, cv = cache
                    ck = kvc.write_prefill_at(ck, k[0], page_row,
                                              cached_len, suffix_len,
                                              page_size)
                    cv = kvc.write_prefill_at(cv, v[0], page_row,
                                              cached_len, suffix_len,
                                              page_size)
                    new_cache = (ck, cv)
                    kctx = kvc.gather_pages(ck, row)
                    vctx = kvc.gather_pages(cv, row)
                # suffix self-attention reads the full-precision k/v
                # (like the cold prefill); only the cached prefix is
                # read back through the (possibly quantized) cache
                o = _cached_context_attention(q, kctx, vctx, k, v,
                                              cached_len)
                return o, new_cache

            x = self._embed(params, tokens, positions)
            x, cache_state = self._layer_scan(params, x,
                                              tuple(cache_state),
                                              positions, attn_hook,
                                              lora_bank=bank,
                                              lora_ids=aids)
            if all_rows:
                logits = jnp.einsum("bsd,dv->bsv", x,
                                    gpt_mod.lm_head(params, cfg))
                return (logits.astype(jnp.float32),) + cache_state
            h = jnp.take(x[0], suffix_len - 1, axis=0)[None, None]
            logits = jnp.einsum("bsd,dv->bsv", h,
                                gpt_mod.lm_head(params, cfg))
            return (logits[:, 0].astype(jnp.float32),) + cache_state

        n_state = len(self.cache.state)
        first = 2 if lora_on else 1
        return jax.jit(prefill_cached,
                       donate_argnums=tuple(range(first,
                                                  first + n_state)))

    def _build_decode(self):
        cfg = self.cfg
        page_size = self.page_size
        impl = self.decode_impl
        quantized = self.kv_dtype == "int8"
        lora_on = self.lora_cfg is not None

        def decode(params, *args):
            """(params, [lora_bank,] *cache_state, tokens [slots] (each
            slot's next input token), lengths [slots] (tokens already
            cached = the new token's absolute position), page_table
            [slots, max_pages][, adapter_ids [slots]]) -> (logits
            [slots, V] f32, *cache_state)."""
            bank = aids = None
            if lora_on:
                bank, *args = args
                *args, aids = args
            *cache_state, tokens, lengths, page_table = args
            positions = lengths[:, None]                   # [B, 1]

            def attn_hook(q, k, v, cache):
                from ray_tpu.ops.attention import decode_attention
                if quantized:
                    ck, cv, cks, cvs = cache
                    kq, ks = self._quantize_kv(k[:, 0])
                    vq, vs = self._quantize_kv(v[:, 0])
                    ck = kvc.write_decode(ck, kq, page_table, lengths,
                                          page_size)
                    cv = kvc.write_decode(cv, vq, page_table, lengths,
                                          page_size)
                    cks = kvc.write_decode(cks, ks, page_table,
                                           lengths, page_size)
                    cvs = kvc.write_decode(cvs, vs, page_table,
                                           lengths, page_size)
                    o = decode_attention(
                        q[:, 0], kvc.gather_pages(ck, page_table),
                        kvc.gather_pages(cv, page_table), lengths + 1,
                        impl=impl,
                        k_scale=kvc.gather_pages(cks, page_table),
                        v_scale=kvc.gather_pages(cvs, page_table))
                    return o[:, None], (ck, cv, cks, cvs)
                ck, cv = cache
                ck = kvc.write_decode(ck, k[:, 0], page_table, lengths,
                                      page_size)
                cv = kvc.write_decode(cv, v[:, 0], page_table, lengths,
                                      page_size)
                kctx = kvc.gather_pages(ck, page_table)
                vctx = kvc.gather_pages(cv, page_table)
                o = decode_attention(q[:, 0], kctx, vctx, lengths + 1,
                                     impl=impl)
                return o[:, None], (ck, cv)

            x = self._embed(params, tokens[:, None], positions)
            x, cache_state = self._layer_scan(params, x,
                                              tuple(cache_state),
                                              positions, attn_hook,
                                              lora_bank=bank,
                                              lora_ids=aids)
            logits = jnp.einsum("bsd,dv->bsv", x,
                                gpt_mod.lm_head(params, cfg))
            return (logits[:, 0].astype(jnp.float32),) + cache_state

        n_state = len(self.cache.state)
        first = 2 if lora_on else 1
        return jax.jit(decode,
                       donate_argnums=tuple(range(first,
                                                  first + n_state)))
