"""GPT serving: the inference engine behind a ``serve`` deployment.

One replica owns one :class:`~ray_tpu.inference.engine.InferenceEngine`
and a single *pump* task that advances ``engine.step()`` in an executor
thread (the compiled step blocks; the event loop must keep accepting
requests while it runs) and fans the ``(rid, token, done)`` events out
to per-request asyncio queues.  Each HTTP/handle request is an async
generator that drains its queue — tokens flow through the existing
``ServeReplica.handle_request_streaming`` path, one object-ref slot per
token, and the handle-side ``DeploymentResponseGenerator`` yields them
as they land.  Continuous batching happens inside the engine: requests
arriving mid-stream join free decode slots without disturbing running
sequences.

Abandoned streams: closing the request's (replica-side) generator —
asyncio cancellation, ``aclose()``, the proxy tearing down a
disconnected HTTP response — cancels the sequence in the engine so its
decode slot frees within a tick.  A *handle* consumer that silently
drops its ``DeploymentResponseGenerator`` does **not** close the
replica-side generator (the object-ref streaming protocol carries no
consumer-liveness signal today), so such requests decode to
``max_new_tokens`` before the slot frees — bound ``max_new_tokens``
accordingly; ref-generator cancellation is an open runtime item.

Usage (see the README serving quickstart)::

    import ray_tpu, ray_tpu.serve as serve
    from ray_tpu.inference.serve_gpt import GPTDeployment

    ray_tpu.init()
    handle = serve.run(GPTDeployment.bind(model="tiny"), name="gpt")
    stream = handle.options(stream=True).remote(
        {"tokens": [1, 2, 3], "max_new_tokens": 8})
    for token in stream:
        ...
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

import ray_tpu.serve as serve
from ray_tpu.inference.sampling import SamplingParams

_PRESETS = ("tiny", "gpt2", "gpt2_medium", "gpt2_large")


def _build_engine(model: str, model_config: Optional[Dict[str, Any]],
                  engine_config: Optional[Dict[str, Any]], seed: int):
    import jax

    from ray_tpu.inference.engine import InferenceEngine
    from ray_tpu.models.gpt import GPTConfig, init_params

    if model not in _PRESETS:
        raise ValueError(f"unknown model preset {model!r}; "
                         f"expected one of {_PRESETS}")
    cfg = getattr(GPTConfig, model)(**(model_config or {}))
    params = init_params(cfg, jax.random.PRNGKey(seed))
    return cfg, InferenceEngine(cfg, params, **(engine_config or {}))


@serve.deployment(max_ongoing_requests=32)
class GPTDeployment:
    """Streaming GPT deployment over the continuous-batching engine.

    ``model``: a ``GPTConfig`` preset name (random-init weights —
    checkpoint loading rides ``train.checkpoint.load_pytree`` via
    ``params`` plumbing once a serving checkpoint format lands);
    ``model_config`` / ``engine_config``: kwargs forwarded to
    ``GPTConfig.<preset>()`` / :class:`InferenceEngine`.

    Request payload (one dict): ``{"tokens": [...], "max_new_tokens":
    int, "temperature": float, "top_k": int, "top_p": float, "seed":
    int, "eos_token": int | None, "logprobs": bool}`` — yields
    generated token ids; with ``"logprobs": True`` each item is
    ``{"token": int, "logprob": float}`` instead (the sampled token's
    model logprob — ``log_softmax`` of the raw logits, parity-tested
    against a teacher-forced recompute in ``tests/test_inference.py``).

    **Load shedding**: with ``RAY_TPU_INFER_MAX_QUEUE`` set, an
    over-cap submit raises
    :class:`~ray_tpu.inference.scheduler.QueueFullError` from the
    request's async generator — the streaming path delivers it as the
    stream's error at first iteration, so the client sees an
    immediate typed rejection (retry / another replica) instead of a
    request parked in an unbounded queue.
    """

    def __init__(self, model: str = "tiny",
                 model_config: Optional[Dict[str, Any]] = None,
                 engine_config: Optional[Dict[str, Any]] = None,
                 seed: int = 0):
        self.cfg, self.engine = _build_engine(model, model_config,
                                              engine_config, seed)
        self._queues: Dict[int, asyncio.Queue] = {}
        self._pump_task: Optional[asyncio.Task] = None

    async def __call__(self, request: Dict[str, Any]):
        sampling = SamplingParams(
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0)),
            seed=int(request.get("seed", 0)))
        want_logprobs = bool(request.get("logprobs", False))
        rid = self.engine.submit(
            request["tokens"],
            max_new_tokens=int(request.get("max_new_tokens", 16)),
            sampling=sampling,
            eos_token=request.get("eos_token"))
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[rid] = queue
        self._ensure_pump()
        try:
            while True:
                item = await queue.get()
                if isinstance(item, BaseException):
                    raise item       # pump died: surface, don't hang
                token, done, logprob = item
                yield ({"token": token, "logprob": logprob}
                       if want_logprobs else token)
                if done:
                    return
        finally:
            self._queues.pop(rid, None)
            # abandoned mid-stream (client disconnect): retire the
            # sequence instead of decoding to max_new_tokens in a slot
            # nobody is reading (no-op for normal completion)
            self.engine.cancel(rid)

    def _ensure_pump(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.get_running_loop().create_task(
                self._pump())

    async def _pump(self) -> None:
        """Advance the engine while any request is in flight; the
        compiled step runs in an executor thread so the event loop
        keeps admitting new requests mid-stream.  A step failure fans
        out to every waiting consumer — a hung stream is worse than a
        failed one."""
        loop = asyncio.get_running_loop()
        try:
            while self.engine.has_work():
                events = await loop.run_in_executor(None,
                                                    self.engine.step)
                for ev in events:
                    rid, token, done = ev
                    queue = self._queues.get(rid)
                    if queue is not None:
                        queue.put_nowait((token, done, ev.logprob))
        except BaseException as e:  # noqa: BLE001 — deliver, then die
            for queue in self._queues.values():
                queue.put_nowait(e)
            raise

    def telemetry_summary(self) -> Dict[str, Any]:
        summary = self.engine.telemetry.summary()
        summary["stats"] = self.engine.stats()
        return summary
