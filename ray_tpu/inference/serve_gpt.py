"""GPT serving: the inference engine behind a ``serve`` deployment.

One replica owns one :class:`~ray_tpu.inference.engine.InferenceEngine`
and a single *pump* task that advances ``engine.step()`` in an executor
thread (the compiled step blocks; the event loop must keep accepting
requests while it runs) and fans the ``(rid, token, done)`` events out
to per-request asyncio queues.  Each HTTP/handle request is an async
generator that drains its queue — tokens flow through the existing
``ServeReplica.handle_request_streaming`` path, one object-ref slot per
token, and the handle-side ``DeploymentResponseGenerator`` yields them
as they land.  Continuous batching happens inside the engine: requests
arriving mid-stream join free decode slots without disturbing running
sequences.

Abandoned streams: closing the request's (replica-side) generator —
asyncio cancellation, ``aclose()``, the proxy tearing down a
disconnected HTTP response — cancels the sequence in the engine so its
decode slot frees within a tick.  A *handle* consumer that silently
drops its ``DeploymentResponseGenerator`` does **not** close the
replica-side generator (the object-ref streaming protocol carries no
consumer-liveness signal today); the **idle-stream reaper**
(``RAY_TPU_INFER_STREAM_IDLE``, default off) covers that hole: a
request whose stream has tokens waiting but has not been pumped for
the budget is cancelled — slot/pages/prefix refcounts released, a
typed :class:`StreamIdleError` left on the queue for any late reader
— instead of decoding to ``max_new_tokens`` for a reader that is
gone.  A consumer merely *waiting* on a slow engine (empty queue) is
never reaped.

Usage (see the README serving quickstart)::

    import ray_tpu, ray_tpu.serve as serve
    from ray_tpu.inference.serve_gpt import GPTDeployment

    ray_tpu.init()
    handle = serve.run(GPTDeployment.bind(model="tiny"), name="gpt")
    stream = handle.options(stream=True).remote(
        {"tokens": [1, 2, 3], "max_new_tokens": 8})
    for token in stream:
        ...
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, Optional

import ray_tpu.serve as serve
from ray_tpu.inference.sampling import SamplingParams

_PRESETS = ("tiny", "gpt2", "gpt2_medium", "gpt2_large")


class ReplicaDrainingError(RuntimeError):
    """Typed admission rejection while the replica drains: new
    requests must go to another replica (the router's retry signal);
    in-flight streams keep decoding to completion."""


class StreamIdleError(RuntimeError):
    """Typed cancellation of an abandoned stream: tokens sat unread
    past ``RAY_TPU_INFER_STREAM_IDLE``, so the request was retired
    (everything released).  A late consumer sees this instead of a
    silent hang on a queue nothing feeds anymore."""


def parse_request(request: Dict[str, Any]) -> Dict[str, Any]:
    """The one parser for the serving payload dict — the deployment
    and the fleet router both route requests through it, so a field
    added to the payload can never silently exist in one path and not
    the other."""
    spec = request.get("speculation")
    spec_k = request.get("speculation_k")
    return {
        "max_new_tokens": int(request.get("max_new_tokens", 16)),
        "sampling": SamplingParams(
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0)),
            seed=int(request.get("seed", 0)),
            # speculation knobs: absent = engine defaults
            # (RAY_TPU_INFER_SPEC{,_K}); explicit values pin this
            # request on or off — a pure throughput knob, outputs are
            # distribution-exact either way
            spec=None if spec is None else bool(spec),
            spec_k=None if spec_k is None else int(spec_k),
            # multi-tenant (r25): which LoRA adapter this request
            # decodes under; absent/None = the base model
            model_id=request.get("model_id")),
        "want_logprobs": bool(request.get("logprobs", False)),
        "eos_token": request.get("eos_token"),
        "ttft_deadline_s": request.get("ttft_deadline_s"),
        "deadline_s": request.get("deadline_s"),
    }


def _build_engine(model: str, model_config: Optional[Dict[str, Any]],
                  engine_config: Optional[Dict[str, Any]], seed: int):
    import jax

    from ray_tpu.inference.engine import InferenceEngine
    from ray_tpu.models.gpt import GPTConfig, init_params

    if model not in _PRESETS:
        raise ValueError(f"unknown model preset {model!r}; "
                         f"expected one of {_PRESETS}")
    cfg = getattr(GPTConfig, model)(**(model_config or {}))
    params = init_params(cfg, jax.random.PRNGKey(seed))
    return cfg, InferenceEngine(cfg, params, **(engine_config or {}))


@serve.deployment(max_ongoing_requests=32)
class GPTDeployment:
    """Streaming GPT deployment over the continuous-batching engine.

    ``model``: a ``GPTConfig`` preset name (random-init weights —
    checkpoint loading rides ``train.checkpoint.load_pytree`` via
    ``params`` plumbing once a serving checkpoint format lands);
    ``model_config`` / ``engine_config``: kwargs forwarded to
    ``GPTConfig.<preset>()`` / :class:`InferenceEngine`.

    Request payload (one dict): ``{"tokens": [...], "max_new_tokens":
    int, "temperature": float, "top_k": int, "top_p": float, "seed":
    int, "eos_token": int | None, "logprobs": bool,
    "ttft_deadline_s": float | None, "deadline_s": float | None,
    "speculation": bool | None, "speculation_k": int | None,
    "model_id": str | None}`` —
    yields generated token ids; with ``"logprobs": True`` each item is
    ``{"token": int, "logprob": float}`` instead (the sampled token's
    model logprob — ``log_softmax`` of the raw logits, parity-tested
    against a teacher-forced recompute in ``tests/test_inference.py``).
    The deadline keys override the ``RAY_TPU_INFER_TTFT_DEADLINE`` /
    ``RAY_TPU_INFER_DEADLINE`` defaults per request; an expired
    request is retired (slot/pages/prefix refcounts released) and its
    stream raises the typed
    :class:`~ray_tpu.inference.scheduler.DeadlineExceededError`.

    **Load shedding**: with ``RAY_TPU_INFER_MAX_QUEUE`` set, an
    over-cap submit raises
    :class:`~ray_tpu.inference.scheduler.QueueFullError` from the
    request's async generator — the streaming path delivers it as the
    stream's error at first iteration, so the client sees an
    immediate typed rejection (retry / another replica) instead of a
    request parked in an unbounded queue.
    """

    def __init__(self, model: str = "tiny",
                 model_config: Optional[Dict[str, Any]] = None,
                 engine_config: Optional[Dict[str, Any]] = None,
                 seed: int = 0,
                 watchdog_s: Optional[float] = None,
                 stream_idle_s: Optional[float] = None):
        self.cfg, self.engine = _build_engine(model, model_config,
                                              engine_config, seed)
        self._queues: Dict[int, asyncio.Queue] = {}
        self._pump_task: Optional[asyncio.Task] = None
        self._draining = False
        from ray_tpu.inference.config import infer_config
        icfg = infer_config()
        watchdog_s = (icfg.watchdog if watchdog_s is None
                      else watchdog_s)
        # idle-stream reaper: rid -> when the consumer last took an
        # item (or the request was submitted); swept by the pump
        self.stream_idle_s = (icfg.stream_idle if stream_idle_s is None
                              else stream_idle_s) or None
        self._last_pumped: Dict[int, float] = {}
        self.streams_reaped = 0
        self._watchdog = None
        if watchdog_s:
            from ray_tpu.resilience.watchdog import EngineWatchdog
            self._watchdog = EngineWatchdog(
                self.engine, timeout_s=watchdog_s).start()

    async def __call__(self, request: Dict[str, Any]):
        if self._draining:
            raise ReplicaDrainingError(
                "replica is draining: admission stopped, in-flight "
                "requests finishing — retry on another replica")
        parsed = parse_request(request)
        want_logprobs = parsed["want_logprobs"]
        # r24: a bare deployment request (no fleet router in front)
        # mints its own trace here — the engine's spans still land in
        # the flight recorder and the dashboard timeline
        from ray_tpu.telemetry import trace as trace_mod
        ctx = trace_mod.mint()
        root_id = trace_mod.record_span(
            "request", ctx, start=time.time(), dur=0.0,
            prompt_tokens=len(request["tokens"]),
            max_new=parsed["max_new_tokens"])
        trace_ctx = ctx.child(root_id) if root_id is not None else ctx
        rid = self.engine.submit(
            request["tokens"],
            max_new_tokens=parsed["max_new_tokens"],
            sampling=parsed["sampling"],
            eos_token=parsed["eos_token"],
            ttft_deadline_s=parsed["ttft_deadline_s"],
            deadline_s=parsed["deadline_s"],
            trace_ctx=trace_ctx)
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[rid] = queue
        self._last_pumped[rid] = time.monotonic()
        self._ensure_pump()
        try:
            while True:
                item = await queue.get()
                # the consumer is live: it just took an item (a
                # consumer *waiting* on an empty queue is tracked by
                # the queue being empty, not by this stamp)
                self._last_pumped[rid] = time.monotonic()
                if isinstance(item, BaseException):
                    raise item       # pump died: surface, don't hang
                token, done, logprob = item
                yield ({"token": token, "logprob": logprob}
                       if want_logprobs else token)
                if done:
                    return
        finally:
            self._queues.pop(rid, None)
            self._last_pumped.pop(rid, None)
            # abandoned mid-stream (client disconnect): retire the
            # sequence instead of decoding to max_new_tokens in a slot
            # nobody is reading (no-op for normal completion)
            self.engine.cancel(rid)

    def _ensure_pump(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.get_running_loop().create_task(
                self._pump())

    async def _pump(self) -> None:
        """Advance the engine while any request is in flight; the
        compiled step runs in an executor thread so the event loop
        keeps admitting new requests mid-stream.  A step failure fans
        out to every waiting consumer — a hung stream is worse than a
        failed one."""
        loop = asyncio.get_running_loop()
        try:
            while True:
                await self._pump_engine(loop)
                if not (self.stream_idle_s and self._queues):
                    return
                # engine idle but unread streams remain (abandoned
                # consumers): keep the reaper alive until they drain
                # or age out — otherwise their queues would persist on
                # a quiescent replica until new traffic revives the
                # pump.  New work re-enters the step loop above.
                self._reap_idle_streams()
                await asyncio.sleep(min(self.stream_idle_s / 4, 0.02))
        except BaseException as e:  # noqa: BLE001 — deliver, then die
            for queue in self._queues.values():
                queue.put_nowait(e)
            raise

    async def _pump_engine(self, loop) -> None:
        while self.engine.has_work():
            events = await loop.run_in_executor(None,
                                                self.engine.step)
            for ev in events:
                rid, token, done = ev
                queue = self._queues.get(rid)
                if queue is None:
                    continue
                if queue.qsize() == 0:
                    # empty -> nonempty: the idle clock measures how
                    # long tokens sit UNREAD, so it starts when the
                    # first unread token lands — not at the last
                    # consumer read (a consumer blocked in get()
                    # through a slow step would otherwise look idle
                    # the moment the token arrives)
                    self._last_pumped[rid] = time.monotonic()
                if ev.error is not None:
                    # deadline expiry: the engine already released
                    # the slot/pages; surface the typed error as the
                    # stream's failure
                    queue.put_nowait(ev.error)
                else:
                    queue.put_nowait((token, done, ev.logprob))
            self._reap_idle_streams()

    def _reap_idle_streams(self) -> None:
        """Cancel requests whose stream has tokens waiting but whose
        consumer has not taken one for ``stream_idle_s`` — the r10
        silently-dropped-generator hole.  An empty queue (consumer
        blocked on a slow engine) never reaps; only unread tokens
        aging out do."""
        if self.stream_idle_s is None:
            return
        now = time.monotonic()
        for rid, queue in list(self._queues.items()):
            if queue.qsize() == 0:
                continue
            if now - self._last_pumped.get(rid, now) \
                    <= self.stream_idle_s:
                continue
            if rid in self.engine._requests:
                self.engine.cancel(rid)
                # a late reader must raise, not hang on a queue the
                # pump no longer feeds
                queue.put_nowait(StreamIdleError(
                    f"request {rid}: stream not pumped for "
                    f"{self.stream_idle_s:.3f}s with tokens waiting "
                    "(RAY_TPU_INFER_STREAM_IDLE) — request "
                    "cancelled, slot/pages released"))
                self.streams_reaped += 1
            # else: the engine already finished it — nothing held and
            # nothing to count; just stop tracking the unread queue
            # (a late reader still drains its buffered tokens to done)
            self._queues.pop(rid, None)
            self._last_pumped.pop(rid, None)

    # ------------------------------------------------------------ drain
    async def drain(self, poll_s: float = 0.05,
                    timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Graceful shutdown: stop admission (``__call__`` raises a
        typed :class:`ReplicaDrainingError` from now on), let every
        in-flight request decode to completion, then report.  The
        autoscaler's scale-down / a preemption notice calls this so a
        replica exits with zero dropped streams; the engine's own
        clean-idle invariants (no held slots/pages) are what "finished"
        means.

        ``timeout_s`` bounds the wait on a pump that is alive but not
        finishing (a wedged step — the watchdog's scenario): past it,
        drain gives up WITHOUT touching engine state (the stuck step
        may still hold it) and reports ``drained: False`` so the
        preemption handler can escalate instead of hanging forever."""
        self._draining = True
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        while True:
            pump_alive = (self._pump_task is not None
                          and not self._pump_task.done())
            if pump_alive:
                if deadline is not None and \
                        time.monotonic() > deadline:
                    # the watchdog stays ARMED: the replica is still
                    # running with a possibly wedged engine — this is
                    # exactly the scenario it reports on
                    stats = self.engine.stats()
                    return {"drained": False,
                            "reason": "pump still running past the "
                                      "drain timeout (wedged step?)",
                            "free_slots": stats["free_slots"],
                            "active": stats["active"],
                            "waiting": stats["waiting"]}
                await asyncio.sleep(poll_s)
                continue
            if self.engine.has_work():
                # the pump is dead (step failure) or never ran, so
                # nothing will tick the engine again: retire every
                # leftover request host-side — the replica must exit
                # with slots/pages/refcounts released, not hang
                # waiting for a tick that cannot come (consumers
                # already got the pump's error fan-out)
                self.engine.drain_requests()
            break
        if self._watchdog is not None:
            self._watchdog.stop()
        stats = self.engine.stats()
        return {"drained": True,
                "requests_done":
                    self.engine.telemetry.summary().get(
                        "requests_done", 0)
                    if self.engine.telemetry.enabled else None,
                "free_slots": stats["free_slots"],
                "active": stats["active"],
                "waiting": stats["waiting"]}

    def telemetry_summary(self) -> Dict[str, Any]:
        summary = self.engine.telemetry.summary()
        summary["stats"] = self.engine.stats()
        summary["draining"] = self._draining
        summary["streams_reaped"] = self.streams_reaped
        if self._watchdog is not None:
            summary["watchdog_wedges"] = self._watchdog.wedges
        return summary
