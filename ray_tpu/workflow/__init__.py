"""Durable workflows (parity: ``python/ray/workflow``): every task's
result is persisted; ``resume`` replays completed steps from storage and
re-executes only the rest.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Callable, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode
from ray_tpu.workflow.events import (EventListener, FileEventListener,
                                     TimerListener, wait_for_event)

__all__ = [
    "init", "run", "resume", "get_status", "list_all", "delete",
    "EventListener", "TimerListener", "FileEventListener",
    "wait_for_event",
]

_storage_root: Optional[str] = None
_remote_fs = None   # fsspec filesystem when the root is a cloud URI


def init(storage: Optional[str] = None) -> None:
    """Set the durable store.  ``storage`` may be a local directory or
    any fsspec URI (``gs://bucket/wf``, ``s3://...``, ``memory://...``)
    — parity: the reference's cloud workflow storage
    (``python/ray/workflow/workflow_storage.py``)."""
    global _storage_root, _remote_fs
    from ray_tpu.train.storage import is_remote_uri
    _storage_root = storage or os.path.expanduser("~/ray_tpu_workflows")
    if is_remote_uri(_storage_root):
        import fsspec
        _remote_fs, _, _ = fsspec.get_fs_token_paths(_storage_root)
        _remote_fs.makedirs(_fs_path(_storage_root), exist_ok=True)
    else:
        _remote_fs = None
        os.makedirs(_storage_root, exist_ok=True)


def _fs_path(uri: str) -> str:
    """Strip the scheme for fsspec filesystem calls."""
    return uri.split("://", 1)[1] if "://" in uri else uri


def _join(*parts: str) -> str:
    if _remote_fs is not None:
        return "/".join(p.rstrip("/") for p in parts)
    return os.path.join(*parts)


def _exists(path: str) -> bool:
    if _remote_fs is not None:
        return _remote_fs.exists(_fs_path(path))
    return os.path.exists(path)


def _read_bytes(path: str) -> bytes:
    if _remote_fs is not None:
        with _remote_fs.open(_fs_path(path), "rb") as f:
            return f.read()
    with open(path, "rb") as f:
        return f.read()


def _write_bytes(path: str, data: bytes) -> None:
    """Durable commit: local writes go tmp + atomic rename; remote
    object stores commit atomically on close."""
    if _remote_fs is not None:
        with _remote_fs.open(_fs_path(path), "wb") as f:
            f.write(data)
        return
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _mkdirs(path: str) -> None:
    if _remote_fs is not None:
        _remote_fs.makedirs(_fs_path(path), exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def _listdir(path: str):
    if _remote_fs is not None:
        return [p.rstrip("/").rsplit("/", 1)[-1]
                for p in _remote_fs.ls(_fs_path(path), detail=False)]
    return os.listdir(path)


def _rmtree(path: str) -> None:
    if _remote_fs is not None:
        try:
            _remote_fs.rm(_fs_path(path), recursive=True)
        except FileNotFoundError:
            pass
    else:
        shutil.rmtree(path, ignore_errors=True)


def _store_dir(workflow_id: str) -> str:
    if _storage_root is None:
        init()
    return _join(_storage_root, workflow_id)


def _step_key(node: FunctionNode, resolved_args) -> str:
    name = getattr(node.remote_fn.func, "__qualname__", "step")
    blob = cloudpickle.dumps((name, resolved_args))
    return f"{name.replace('.', '_')}-{hashlib.sha1(blob).hexdigest()[:12]}"


def _run_node(node: Any, wf_dir: str, cache: Dict[int, Any]):
    if not isinstance(node, DAGNode):
        return node
    if id(node) in cache:
        return cache[id(node)]
    if not isinstance(node, FunctionNode):
        raise TypeError("workflows support function DAGs")
    args = [_run_node(a, wf_dir, cache) for a in node.args]
    kwargs = {k: _run_node(v, wf_dir, cache)
              for k, v in node.kwargs.items()}
    key = _step_key(node, (args, kwargs))
    result_path = _join(wf_dir, f"{key}.pkl")
    if _exists(result_path):
        value = cloudpickle.loads(_read_bytes(result_path))
    else:
        value = ray_tpu.get(node.remote_fn.remote(*args, **kwargs),
                            timeout=600)
        _write_bytes(result_path, cloudpickle.dumps(value))
    cache[id(node)] = value
    return value


def run(dag: FunctionNode, *, workflow_id: str) -> Any:
    """Execute a DAG durably; completed steps are checkpointed."""
    wf_dir = _store_dir(workflow_id)
    _mkdirs(wf_dir)
    _write_bytes(_join(wf_dir, "status.json"),
                 json.dumps({"status": "RUNNING"}).encode())
    try:
        result = _run_node(dag, wf_dir, {})
    except BaseException:
        _write_bytes(_join(wf_dir, "status.json"),
                     json.dumps({"status": "FAILED"}).encode())
        raise
    _write_bytes(_join(wf_dir, "output.pkl"), cloudpickle.dumps(result))
    _write_bytes(_join(wf_dir, "status.json"),
                 json.dumps({"status": "SUCCESSFUL"}).encode())
    return result


def resume(workflow_id: str, dag: Optional[FunctionNode] = None) -> Any:
    """Resume: replay persisted steps, run the rest (dag required unless
    the workflow finished, in which case the stored output is returned)."""
    wf_dir = _store_dir(workflow_id)
    out_path = _join(wf_dir, "output.pkl")
    if _exists(out_path):
        return cloudpickle.loads(_read_bytes(out_path))
    if dag is None:
        raise ValueError(
            f"workflow {workflow_id!r} is incomplete; pass its dag to "
            "resume execution")
    return run(dag, workflow_id=workflow_id)


def get_status(workflow_id: str) -> str:
    path = _join(_store_dir(workflow_id), "status.json")
    if not _exists(path):
        return "NOT_FOUND"
    return json.loads(_read_bytes(path))["status"]


def list_all() -> Dict[str, str]:
    if _storage_root is None:
        init()
    out = {}
    for wf in _listdir(_storage_root):
        out[wf] = get_status(wf)
    return out


def delete(workflow_id: str) -> None:
    _rmtree(_store_dir(workflow_id))
