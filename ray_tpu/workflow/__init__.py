"""Durable workflows (parity: ``python/ray/workflow``): every task's
result is persisted; ``resume`` replays completed steps from storage and
re-executes only the rest.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Callable, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode

_storage_root: Optional[str] = None


def init(storage: Optional[str] = None) -> None:
    global _storage_root
    _storage_root = storage or os.path.expanduser("~/ray_tpu_workflows")
    os.makedirs(_storage_root, exist_ok=True)


def _store_dir(workflow_id: str) -> str:
    if _storage_root is None:
        init()
    return os.path.join(_storage_root, workflow_id)


def _step_key(node: FunctionNode, resolved_args) -> str:
    name = getattr(node.remote_fn.func, "__qualname__", "step")
    blob = cloudpickle.dumps((name, resolved_args))
    return f"{name.replace('.', '_')}-{hashlib.sha1(blob).hexdigest()[:12]}"


def _run_node(node: Any, wf_dir: str, cache: Dict[int, Any]):
    if not isinstance(node, DAGNode):
        return node
    if id(node) in cache:
        return cache[id(node)]
    if not isinstance(node, FunctionNode):
        raise TypeError("workflows support function DAGs")
    args = [_run_node(a, wf_dir, cache) for a in node.args]
    kwargs = {k: _run_node(v, wf_dir, cache)
              for k, v in node.kwargs.items()}
    key = _step_key(node, (args, kwargs))
    result_path = os.path.join(wf_dir, f"{key}.pkl")
    if os.path.exists(result_path):
        with open(result_path, "rb") as f:
            value = cloudpickle.load(f)
    else:
        value = ray_tpu.get(node.remote_fn.remote(*args, **kwargs),
                            timeout=600)
        tmp = result_path + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, result_path)  # durable commit
    cache[id(node)] = value
    return value


def run(dag: FunctionNode, *, workflow_id: str) -> Any:
    """Execute a DAG durably; completed steps are checkpointed."""
    wf_dir = _store_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    with open(os.path.join(wf_dir, "status.json"), "w") as f:
        json.dump({"status": "RUNNING"}, f)
    try:
        result = _run_node(dag, wf_dir, {})
    except BaseException:
        with open(os.path.join(wf_dir, "status.json"), "w") as f:
            json.dump({"status": "FAILED"}, f)
        raise
    with open(os.path.join(wf_dir, "output.pkl"), "wb") as f:
        cloudpickle.dump(result, f)
    with open(os.path.join(wf_dir, "status.json"), "w") as f:
        json.dump({"status": "SUCCESSFUL"}, f)
    return result


def resume(workflow_id: str, dag: Optional[FunctionNode] = None) -> Any:
    """Resume: replay persisted steps, run the rest (dag required unless
    the workflow finished, in which case the stored output is returned)."""
    wf_dir = _store_dir(workflow_id)
    out_path = os.path.join(wf_dir, "output.pkl")
    if os.path.exists(out_path):
        with open(out_path, "rb") as f:
            return cloudpickle.load(f)
    if dag is None:
        raise ValueError(
            f"workflow {workflow_id!r} is incomplete; pass its dag to "
            "resume execution")
    return run(dag, workflow_id=workflow_id)


def get_status(workflow_id: str) -> str:
    path = os.path.join(_store_dir(workflow_id), "status.json")
    if not os.path.exists(path):
        return "NOT_FOUND"
    with open(path) as f:
        return json.load(f)["status"]


def list_all() -> Dict[str, str]:
    if _storage_root is None:
        init()
    out = {}
    for wf in os.listdir(_storage_root):
        out[wf] = get_status(wf)
    return out


def delete(workflow_id: str) -> None:
    shutil.rmtree(_store_dir(workflow_id), ignore_errors=True)
