"""Workflow events (parity: ``python/ray/workflow/event_listener.py``).

An *event* is an external happening a durable workflow waits on —
a timer, a file landing, a message — expressed as an
:class:`EventListener` whose ``poll_for_event`` blocks until the event
occurs.  ``workflow.wait_for_event(Listener, *args)`` runs the listener
as a workflow step: the wait participates in durable replay, so a
resumed workflow that already observed the event does NOT wait again —
the recorded payload replays instead (checkpointed like any other step
result).
"""

from __future__ import annotations

import time
from typing import Any


class EventListener:
    """Subclass with an async (or sync) ``poll_for_event``."""

    async def poll_for_event(self, *args, **kwargs) -> Any:
        raise NotImplementedError

    async def event_checkpointed(self, event: Any) -> None:
        """Commit hook: called after the event payload is durably
        recorded (override for exactly-once sources needing acks)."""


class TimerListener(EventListener):
    """Fires at an absolute unix timestamp (reference example)."""

    async def poll_for_event(self, fire_at: float) -> float:
        import asyncio
        delay = fire_at - time.time()
        if delay > 0:
            await asyncio.sleep(delay)
        return fire_at


class FileEventListener(EventListener):
    """Fires when a path exists; payload is the file's contents."""

    async def poll_for_event(self, path: str,
                             poll_interval_s: float = 0.1) -> bytes:
        import asyncio
        import os
        while not os.path.exists(path):
            await asyncio.sleep(poll_interval_s)
        with open(path, "rb") as f:
            return f.read()


def wait_for_event(listener_cls, *args, **kwargs):
    """Workflow step wrapper: returns a bound step callable for use
    inside ``workflow.run`` graphs (the listener's poll result is the
    step's durable output)."""
    import asyncio

    def _wait(*a, **kw):
        listener = listener_cls()
        event = asyncio.run(listener.poll_for_event(*args, **kwargs))
        asyncio.run(listener.event_checkpointed(event))
        return event

    _wait.__name__ = f"wait_for_event[{listener_cls.__name__}]"
    return _wait
