"""External experiment-tracker integrations (parity:
``python/ray/air/integrations``): import the submodule for the tracker
you use — each degrades to a clear ImportError when the client library
is not in the image."""
