"""MLflow tracking (parity: ``python/ray/air/integrations/mlflow.py``
MLflowLoggerCallback).

One MLflow run per trial; reports become metrics, trial config becomes
params.  The ``mlflow`` client is not part of the TPU image —
construction raises a clear ImportError when absent."""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.callbacks import LoggerCallback


class MLflowLoggerCallback(LoggerCallback):
    def __init__(self, tracking_uri: Optional[str] = None,
                 experiment_name: Optional[str] = None,
                 tags: Optional[Dict[str, str]] = None):
        try:
            import mlflow
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError(
                "MLflowLoggerCallback requires the `mlflow` package in "
                "the image (TPU pods run without runtime pip installs)"
            ) from e
        self._mlflow = mlflow
        if tracking_uri:
            mlflow.set_tracking_uri(tracking_uri)
        if experiment_name:
            mlflow.set_experiment(experiment_name)
        self.tags = tags or {}
        self._runs: Dict[str, Any] = {}

    def log_trial_result(self, trial, result: Dict[str, Any]) -> None:
        tid = trial.trial_id
        if tid not in self._runs:
            run = self._mlflow.start_run(run_name=tid, nested=True,
                                         tags=self.tags)
            self._runs[tid] = run
            for k, v in (getattr(trial, "config", {}) or {}).items():
                try:
                    self._mlflow.log_param(k, v)
                except Exception:  # noqa: BLE001 - non-loggable param
                    pass
        step = int(result.get("training_iteration", 0))
        self._mlflow.log_metrics(
            {k: float(v) for k, v in result.items()
             if isinstance(v, (int, float)) and not isinstance(v, bool)},
            step=step)

    def log_trial_end(self, trial, failed: bool) -> None:
        if self._runs.pop(trial.trial_id, None) is not None:
            self._mlflow.end_run("FAILED" if failed else "FINISHED")
