"""MLflow tracking (parity: ``python/ray/air/integrations/mlflow.py``
MLflowLoggerCallback).

One MLflow run per trial, driven through ``MlflowClient`` with explicit
run ids — the fluent module-level API binds to a single global "active
run", which cross-writes metrics/artifacts between concurrently
reporting trials.  Config becomes params, reports become step-indexed
metrics, persisted checkpoints optionally upload as run artifacts
(off-thread; the hook runs in the Tuner's controller loop), and the
terminal status lands on completion.  The ``mlflow`` client is not part
of the TPU image — construction raises a clear ImportError when
absent."""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ray_tpu.tune.callbacks import LoggerCallback


class MLflowLoggerCallback(LoggerCallback):
    def __init__(self, tracking_uri: Optional[str] = None,
                 experiment_name: Optional[str] = None,
                 tags: Optional[Dict[str, str]] = None,
                 save_artifact: bool = False):
        try:
            import mlflow
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError(
                "MLflowLoggerCallback requires the `mlflow` package in "
                "the image (TPU pods run without runtime pip installs)"
            ) from e
        self._mlflow = mlflow
        self._client = mlflow.tracking.MlflowClient(
            tracking_uri=tracking_uri)
        self._experiment_id = "0"
        if experiment_name:
            exp = self._client.get_experiment_by_name(experiment_name)
            if exp is None:
                self._experiment_id = self._client.create_experiment(
                    experiment_name)
            else:
                self._experiment_id = exp.experiment_id
        self.tags = tags or {}
        self.save_artifact = save_artifact
        self._run_ids: Dict[str, str] = {}

    def _run_id(self, trial) -> str:
        tid = trial.trial_id
        rid = self._run_ids.get(tid)
        if rid is None:
            run = self._client.create_run(
                self._experiment_id,
                tags={**self.tags, "mlflow.runName": tid})
            rid = run.info.run_id
            self._run_ids[tid] = rid
            for k, v in (getattr(trial, "config", {}) or {}).items():
                try:
                    self._client.log_param(rid, k, v)
                except Exception:  # noqa: BLE001 - non-loggable param
                    pass
        return rid

    def log_trial_result(self, trial, result: Dict) -> None:
        rid = self._run_id(trial)
        step = int(result.get("training_iteration", 0))
        ts = int(time.time() * 1000)
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self._client.log_metric(rid, k, float(v),
                                        timestamp=ts, step=step)

    def log_trial_save(self, trial, checkpoint_path: str) -> None:
        """Persisted checkpoint -> MLflow run artifacts (off-thread)."""
        if not self.save_artifact:
            return
        rid = self._run_id(trial)

        def upload():
            try:
                self._client.log_artifacts(
                    rid, checkpoint_path,
                    artifact_path=f"checkpoints/{trial.trial_id}")
            except Exception:  # noqa: BLE001 — upload is best-effort
                pass

        threading.Thread(target=upload, daemon=True,
                         name="mlflow-ckpt-upload").start()

    def log_trial_end(self, trial, failed: bool) -> None:
        rid = self._run_ids.pop(trial.trial_id, None)
        if rid is not None:
            self._client.set_terminated(
                rid, "FAILED" if failed else "FINISHED")
