"""Weights & Biases tracking (parity:
``python/ray/air/integrations/wandb.py`` WandbLoggerCallback).

Full run lifecycle per trial: config capture (all params, not just
numerics), every ``tune.report`` logged at its training iteration,
optional checkpoint artifact upload on each persisted checkpoint, and
a final summary + exit status on completion.  The ``wandb`` client is
not part of the TPU image — construction raises a clear ImportError
when absent (reference behavior)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.callbacks import LoggerCallback


class WandbLoggerCallback(LoggerCallback):
    def __init__(self, project: Optional[str] = None,
                 group: Optional[str] = None,
                 api_key: Optional[str] = None,
                 upload_checkpoints: bool = False,
                 **wandb_init_kwargs):
        try:
            import wandb
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError(
                "WandbLoggerCallback requires the `wandb` package in "
                "the image (TPU pods run without runtime pip installs)"
            ) from e
        self._wandb = wandb
        if api_key:
            wandb.login(key=api_key)
        self.project = project
        self.group = group
        self.upload_checkpoints = upload_checkpoints
        self.kwargs = wandb_init_kwargs
        self._runs: Dict[str, Any] = {}

    def _run(self, trial):
        tid = trial.trial_id
        run = self._runs.get(tid)
        if run is None:
            kwargs = dict(self.kwargs)
            # merge, don't collide, with user-supplied tags
            kwargs["tags"] = list(kwargs.get("tags") or []) \
                + [f"trial:{tid}"]
            run = self._wandb.init(
                project=self.project, group=self.group, name=tid,
                config=dict(getattr(trial, "config", {}) or {}),
                reinit=True, **kwargs)
            self._runs[tid] = run
        return run

    def log_trial_result(self, trial, result: Dict[str, Any]) -> None:
        run = self._run(trial)
        step = int(result.get("training_iteration", 0)) or None
        run.log({k: v for k, v in result.items()
                 if isinstance(v, (int, float))
                 and not isinstance(v, bool)}, step=step)

    def log_trial_save(self, trial, checkpoint_path: str) -> None:
        """Persisted checkpoint -> W&B artifact (versioned per trial).

        Uploaded off-thread: the hook runs in the Tuner's controller
        loop, and a multi-GB artifact push must not stall every other
        trial's scheduling for the duration (the reference isolates
        wandb in a separate process for the same reason)."""
        if not self.upload_checkpoints:
            return
        run = self._run(trial)

        def upload():
            try:
                art = self._wandb.Artifact(
                    f"checkpoint_{trial.trial_id}", type="model")
                art.add_dir(checkpoint_path)
                run.log_artifact(art)
            except Exception:  # noqa: BLE001 — upload is best-effort
                pass

        import threading
        threading.Thread(target=upload, daemon=True,
                         name="wandb-ckpt-upload").start()

    def log_trial_end(self, trial, failed: bool) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            # final summary: last reported result, incl. non-numerics
            last = getattr(trial, "last_result", None) or {}
            for k, v in last.items():
                if k != "config":
                    try:
                        run.summary[k] = v
                    except Exception:  # noqa: BLE001
                        pass
            run.finish(exit_code=1 if failed else 0)
