"""Weights & Biases tracking (parity:
``python/ray/air/integrations/wandb.py`` WandbLoggerCallback).

One W&B run per trial; every ``tune.report`` becomes a ``wandb.log``.
The ``wandb`` client is not part of the TPU image — construction raises
a clear ImportError when absent (reference behavior)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.callbacks import LoggerCallback


class WandbLoggerCallback(LoggerCallback):
    def __init__(self, project: Optional[str] = None,
                 group: Optional[str] = None,
                 api_key: Optional[str] = None, **wandb_init_kwargs):
        try:
            import wandb
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError(
                "WandbLoggerCallback requires the `wandb` package in "
                "the image (TPU pods run without runtime pip installs)"
            ) from e
        self._wandb = wandb
        if api_key:
            wandb.login(key=api_key)
        self.project = project
        self.group = group
        self.kwargs = wandb_init_kwargs
        self._runs: Dict[str, Any] = {}

    def log_trial_result(self, trial, result: Dict[str, Any]) -> None:
        tid = trial.trial_id
        run = self._runs.get(tid)
        if run is None:
            run = self._wandb.init(
                project=self.project, group=self.group, name=tid,
                config=dict(getattr(trial, "config", {}) or {}),
                reinit=True, **self.kwargs)
            self._runs[tid] = run
        run.log({k: v for k, v in result.items()
                 if isinstance(v, (int, float)) and not isinstance(v, bool)})

    def log_trial_end(self, trial, failed: bool) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.finish(exit_code=1 if failed else 0)
