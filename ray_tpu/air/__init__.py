"""AIR-shared plumbing (parity: ``python/ray/air``): run/checkpoint
configs live in ``ray_tpu.train.config``; tracker integrations in
``ray_tpu.air.integrations``."""

from ray_tpu.train.config import (CheckpointConfig, FailureConfig,
                                  RunConfig, ScalingConfig)

__all__ = ["CheckpointConfig", "FailureConfig", "RunConfig",
           "ScalingConfig"]
