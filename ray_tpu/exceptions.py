"""User-visible exception types.

Parity with the reference's ``python/ray/exceptions.py``: task errors wrap
the remote traceback and re-raise at ``get`` time; actor/object errors carry
the relevant IDs.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RayError(RayTpuError):
    """Alias kept for API familiarity."""


class TaskError(RayError):
    """A task raised an exception; re-raised from ``get``.

    Carries the remote traceback string so the user sees the real failure
    site (reference behavior: ``RayTaskError`` in ``python/ray/exceptions.py``).
    """

    def __init__(self, cause: BaseException, remote_tb: str = "",
                 task_id: Optional[str] = None, proctitle: str = ""):
        self.cause = cause
        self.remote_tb = remote_tb
        self.task_id = task_id
        self.proctitle = proctitle
        super().__init__(str(cause))

    def __str__(self):
        msg = f"{type(self.cause).__name__}: {self.cause}"
        if self.remote_tb:
            msg += "\n\nremote traceback:\n" + self.remote_tb
        return msg

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that is-a ``type(cause)`` for except clauses."""
        cause = self.cause
        # errors that crossed intermediate tasks arrive as nested
        # TaskErrors (see _Wrapped.__reduce__) — surface the original
        # type so `except ValueError:` keeps matching across hops
        while isinstance(cause, TaskError):
            cause = cause.cause
        if cause is not self.cause:
            return TaskError(cause, self.remote_tb,
                             self.task_id).as_instanceof_cause()
        cause_cls = type(self.cause)
        if cause_cls in (SystemExit, KeyboardInterrupt):
            return self
        try:
            class _Wrapped(TaskError, cause_cls):  # type: ignore[misc]
                def __init__(wrapped_self):
                    # set TaskError's state directly instead of calling
                    # TaskError.__init__: its cooperative
                    # ``super().__init__(str(cause))`` would continue
                    # down _Wrapped's MRO INTO cause_cls.__init__ —
                    # a cause class with a non-(message) constructor
                    # (DeadlineExceededError, InjectedFault, ...) then
                    # raised TypeError and the wrap silently degraded
                    # to a plain TaskError that except-cause_cls
                    # clauses no longer caught
                    wrapped_self.cause = self.cause
                    wrapped_self.remote_tb = self.remote_tb
                    wrapped_self.task_id = self.task_id
                    wrapped_self.proctitle = self.proctitle
                    Exception.__init__(wrapped_self, str(self.cause))

                def __reduce__(wrapped_self):
                    # the dynamic class can't unpickle (cause_cls's
                    # __reduce__ would call __init__ with its own args);
                    # cross process boundaries as a plain TaskError and
                    # get re-wrapped at the final raise site
                    return (TaskError, (self.cause, self.remote_tb,
                                        self.task_id))
            _Wrapped.__name__ = f"TaskError({cause_cls.__name__})"
            _Wrapped.__qualname__ = _Wrapped.__name__
            return _Wrapped()
        except TypeError:
            return self


RayTaskError = TaskError


class WorkerCrashedError(RayError):
    """The worker process executing the task died unexpectedly."""


class ActorError(RayError):
    pass


RayActorError = ActorError


class ActorDiedError(ActorError):
    def __init__(self, actor_id: str = "", reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(
            f"Actor {actor_id} is dead: {reason or 'actor process exited'}")


class ActorUnavailableError(ActorError):
    """Actor is restarting; the call may be retried."""


class InfeasibleTaskError(RayError):
    """No node in the cluster can ever satisfy the task's resources."""


class ObjectLostError(RayError):
    def __init__(self, object_id: str = "", reason: str = ""):
        self.object_id = object_id
        super().__init__(
            f"Object {object_id} is lost: {reason or 'all copies failed'}")


class OwnerDiedError(ObjectLostError):
    """The worker/node owning an object's refcount + lifetime died.

    Parity: reference ``OwnerDiedError`` (``python/ray/exceptions.py``) —
    an object whose owner is gone is unrecoverable unless lineage can
    recompute it (task returns); ``ray.put`` objects fate-share with
    their owner."""

    def __init__(self, object_id: str = "", reason: str = ""):
        super().__init__(object_id, reason or "the object's owner died")


class ObjectStoreFullError(RayError):
    pass


class OutOfMemoryError(RayError):
    """Task killed by the node memory monitor."""


class GetTimeoutError(RayError, TimeoutError):
    pass


class TaskCancelledError(RayError):
    def __init__(self, task_id: Optional[str] = None):
        self.task_id = task_id
        super().__init__(f"Task {task_id or ''} was cancelled")


class PendingCallsLimitExceeded(RayError):
    pass


class RuntimeEnvSetupError(RayError):
    pass


class NodeDiedError(RayError):
    pass


class RaySystemError(RayError):
    pass


def format_remote_traceback(exc: BaseException) -> str:
    return "".join(
        traceback.format_exception(type(exc), exc, exc.__traceback__))
