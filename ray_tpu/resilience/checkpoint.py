"""Async checkpoint/resume for training: survive preemption bit-exactly.

TPU fleets are preemptible (Podracer, arXiv:2104.06272, makes
checkpoint-resume the load-bearing answer), so a multi-hour
``build_gpt_train`` run must be able to die at any step and continue
as if nothing happened.  Two pieces:

- :class:`TrainCheckpointer` — snapshots the **full** resume state
  (the donated :class:`~ray_tpu.models.training.TrainState` — params,
  opt state, step counter — plus caller extras like the data cursor
  and PRNG key) to host on the training thread, then hands the disk
  write to a **background thread**: the steady-state step loop only
  pays the device->host copy every ``RAY_TPU_CKPT_EVERY`` steps, never
  the filesystem.  Writes go through the existing orbax/npz path
  (``train/checkpoint.py:save_pytree``) into
  ``train/checkpoint_manager.py`` retention (keep
  ``RAY_TPU_CKPT_KEEP`` newest), so the on-disk layout is the same
  ``checkpoint_NNNNNN`` family every other trainer here writes.

- :meth:`TrainCheckpointer.restore_latest` — walks the retained
  snapshots newest-first, **validating** each restored tree against
  the live state's structure/shapes/dtypes, and falls back *loudly* to
  the previous retained snapshot on a torn or corrupt one (truncated
  orbax dir, npz/sidecar mismatch) instead of crashing or silently
  loading garbage.

Resume is bit-exact by construction: the snapshot is taken *between*
steps (after step N's state materialized, before step N+1 donates it),
and the data cursor restores the exact batch sequence — the loss
sequence after :func:`run_train_ckpt_loop` resumes is identical to an
uninterrupted run's (asserted in ``tests/test_resilience.py``).

Failure policy: a checkpoint write that raises (disk full, injected
``ckpt.write`` fault) is counted and warned, never propagated — the
checkpointer must not kill the run it exists to protect.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.resilience.config import resilience_config
from ray_tpu.train.checkpoint import (Checkpoint, load_pytree,
                                      save_pytree)
from ray_tpu.train.checkpoint_manager import CheckpointManager
from ray_tpu.train.config import CheckpointConfig
from ray_tpu.util import chaos

_STATE_NAME = "train_state"


def _mesh_spec(mesh):
    """Mesh / MeshSpec / None -> MeshSpec or None (the sidecar form)."""
    if mesh is None:
        return None
    from ray_tpu.parallel.mesh import MeshSpec
    return MeshSpec.from_mesh(mesh)


def _host_tree(tree):
    """Device pytree -> host (numpy) pytree.  Blocks until the leaves'
    producing computation is done — which is exactly the between-steps
    barrier that makes the snapshot a consistent cut.  (Plain
    ``np.asarray``: ``ascontiguousarray`` would promote the 0-d step
    counter to shape ``(1,)`` and break shape validation on restore.)"""
    import jax
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _validate_tree(restored, example) -> None:
    """Raise ``ValueError`` unless ``restored`` matches ``example``'s
    structure and per-leaf shape/dtype.  The npz fallback path can
    deserialize a *wrong* tree without erroring (the arrays load fine,
    they just aren't this model's), and training on garbage params is
    strictly worse than failing over to an older snapshot."""
    import jax
    rl, rt = jax.tree.flatten(restored)
    el, et = jax.tree.flatten(example)
    if rt != et:
        raise ValueError(f"checkpoint tree structure mismatch: "
                         f"{rt} != {et}")
    for i, (r, e) in enumerate(zip(rl, el)):
        r_shape, e_shape = np.shape(r), np.shape(e)
        r_dtype = np.asarray(r).dtype if not hasattr(r, "dtype") \
            else r.dtype
        e_dtype = np.asarray(e).dtype if not hasattr(e, "dtype") \
            else e.dtype
        if tuple(r_shape) != tuple(e_shape) or \
                np.dtype(r_dtype) != np.dtype(e_dtype):
            raise ValueError(
                f"checkpoint leaf {i} mismatch: restored "
                f"{r_dtype}{list(r_shape)} vs expected "
                f"{e_dtype}{list(e_shape)}")


def _truncate_dir(path: str) -> None:
    """Corrupt a just-written checkpoint (the ``ckpt.truncate`` fault
    action): delete the second half of its files, depth-first — enough
    to tear either the orbax layout or the npz+sidecar pair."""
    files: List[str] = []
    for root, _dirs, names in os.walk(path):
        files.extend(os.path.join(root, n) for n in sorted(names))
    for f in files[len(files) // 2:] or files:
        try:
            os.remove(f)
        except OSError:
            pass


class TrainCheckpointer:
    """Async snapshot writer + corrupt-tolerant restorer.

    ``maybe_save(state, step=...)`` is the hot-path call: a no-op
    unless ``step`` is a multiple of ``every``; on trigger it copies
    the state to host (the only cost the step loop sees) and enqueues
    the write.  The background thread persists through
    ``save_pytree`` and registers with a
    :class:`~ray_tpu.train.checkpoint_manager.CheckpointManager`
    (``resume=True``: a restarted process adopts the prior run's
    snapshots — that is the whole point here), which prunes to the
    ``keep`` newest.  ``flush()`` blocks until the write queue drains
    (call before measuring or exiting); ``close()`` flushes and stops
    the thread.

    The write queue is bounded at 2: if writes are slower than the
    cadence, ``save`` blocks rather than buffering an unbounded trail
    of host snapshots (each is a full model copy).
    """

    def __init__(self, directory: Optional[str] = None, *,
                 every: Optional[int] = None,
                 keep: Optional[int] = None,
                 mesh=None,
                 accum_steps: Optional[int] = None,
                 label: str = "train",
                 telemetry=None):
        rcfg = resilience_config()
        self.directory = directory or rcfg.ckpt_dir
        if self.directory is None:
            raise ValueError("TrainCheckpointer needs a directory "
                             "(argument or RAY_TPU_CKPT_DIR)")
        self.every = rcfg.ckpt_every if every is None else int(every)
        if self.every < 0:
            raise ValueError(f"every must be >= 0, got {self.every} "
                             "(check RAY_TPU_CKPT_EVERY)")
        keep = rcfg.ckpt_keep if keep is None else int(keep)
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep} "
                             "(check RAY_TPU_CKPT_KEEP)")
        os.makedirs(self.directory, exist_ok=True)
        self.manager = CheckpointManager(
            self.directory, CheckpointConfig(num_to_keep=keep),
            resume=True)
        from ray_tpu.telemetry.ckpt import CkptTelemetry
        from ray_tpu.telemetry.config import TelemetryConfig
        config = (TelemetryConfig(enabled=bool(telemetry))
                  if isinstance(telemetry, bool) else None)
        self.telemetry = CkptTelemetry(label=label, config=config)
        # default elastic sidecar (per-save mesh=/accum_steps= override
        # it — the elastic loop's topology changes mid-run)
        self.mesh_spec = _mesh_spec(mesh)
        self.accum_steps = (None if accum_steps is None
                            else int(accum_steps))
        self.write_errors: List[str] = []
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        self._lock = threading.Lock()   # manager index/registration
        self._thread = threading.Thread(target=self._writer,
                                        daemon=True,
                                        name="ckpt-writer")
        self._thread.start()

    # -------------------------------------------------------- hot path
    def maybe_save(self, state, *, step: int,
                   extras: Optional[Dict[str, Any]] = None,
                   mesh=None,
                   accum_steps: Optional[int] = None) -> bool:
        """Checkpoint iff ``every`` is on and ``step % every == 0``.
        Returns True when a snapshot was taken (write still async)."""
        if not self.every or step % self.every:
            return False
        self.save(state, step=step, extras=extras, mesh=mesh,
                  accum_steps=accum_steps)
        return True

    def save(self, state, *, step: int,
             extras: Optional[Dict[str, Any]] = None,
             mesh=None,
             accum_steps: Optional[int] = None) -> None:
        """Snapshot now: host copy on this thread, write in background.

        ``mesh``/``accum_steps`` override the constructor defaults for
        this snapshot's elastic sidecar — the writing topology and
        accumulation factor ride the checkpoint metadata so a restore
        onto a *different* mesh is a decision
        (:meth:`restore_latest` ``reshard=True``), never an accident."""
        payload = {
            "state": _host_tree(state),
            "extras": {k: np.asarray(v)
                       for k, v in (extras or {}).items()},
        }
        spec = _mesh_spec(mesh) if mesh is not None else self.mesh_spec
        accum = self.accum_steps if accum_steps is None \
            else int(accum_steps)
        sidecar: Dict[str, Any] = {}
        if spec is not None:
            sidecar["mesh"] = spec.to_dict()
        if accum is not None:
            sidecar["accum_steps"] = accum
        self._q.put((payload, int(step), sidecar))

    # ------------------------------------------------------- background
    def _writer(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                self._q.task_done()
                return
            payload, step, sidecar = job
            try:
                t0 = time.monotonic()
                chaos.maybe_fail("ckpt.write")
                with self._lock:
                    idx = self.manager._index
                    dest = os.path.join(self.directory,
                                        f"checkpoint_{idx:06d}")
                    save_pytree(payload, dest, name=_STATE_NAME)
                    ckpt_obj = Checkpoint(dest)
                    if sidecar:
                        # the elastic block rides the checkpoint's own
                        # .metadata.json (one JSON for both the orbax
                        # and npz state formats)
                        ckpt_obj.set_metadata({"elastic": sidecar})
                    if chaos.should_fire("ckpt.truncate"):
                        _truncate_dir(dest)
                    self.manager.register(ckpt_obj,
                                          metrics={"step": step})
                self.telemetry.record_write(time.monotonic() - t0,
                                            step=step)
            except Exception as e:  # noqa: BLE001 — never kill the run
                self.telemetry.record_failure()
                self.write_errors.append(f"step {step}: {e!r}")
                print(f"checkpoint write for step {step} failed "
                      f"({e!r}); training continues on the previous "
                      "retained snapshot", file=sys.stderr)
            finally:
                self._q.task_done()

    def flush(self) -> None:
        """Block until every enqueued write has been attempted."""
        self._q.join()

    def close(self) -> None:
        self.flush()
        self._q.put(None)
        self._thread.join(timeout=30)

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------- restore
    def restore_latest(self, example=None, *, mesh=None,
                       reshard: bool = False
                       ) -> Optional[Dict[str, Any]]:
        """Newest restorable snapshot, or None when the directory holds
        nothing usable.

        Walks retained checkpoints newest-first; each candidate is
        loaded and (when ``example`` — a live payload-shaped pytree —
        is given) validated leaf-by-leaf.  A candidate that fails to
        load **or** validate is skipped with a loud stderr warning and
        the walk falls back to the previous retained snapshot: a
        truncated orbax dir or an npz whose sidecar disagrees with the
        model must cost one checkpoint interval of progress, not the
        run (and must never train on silently-wrong arrays).

        ``mesh``: the topology the caller intends to restore onto.
        When the snapshot's elastic sidecar records a *different*
        writing mesh, restore raises a typed
        :class:`~ray_tpu.resilience.elastic.MeshMismatchError` unless
        ``reshard=True`` — the state's host arrays place onto any
        dividing mesh (``resilience.elastic.reshard_state``), but that
        must be a decision, not a drive-by.  Snapshots written before
        the sidecar existed (no ``elastic`` block) restore as before
        — back-compat over strictness for data that cannot know.

        Returns ``{"state", "extras", "step", "path", "mesh",
        "accum_steps"}`` (``mesh``: the recorded
        :class:`~ray_tpu.parallel.mesh.MeshSpec` or None;
        ``accum_steps``: the recorded factor or None).
        """
        self.flush()
        with self._lock:
            candidates = list(self.manager.best_checkpoints())
        for ckpt, metrics in candidates:     # newest first (recency)
            # the sidecar is one small JSON — check the topology
            # BEFORE deserializing a potentially multi-GB state that
            # a mismatch would only throw away
            sidecar = ckpt.get_metadata().get("elastic", {})
            recorded = sidecar.get("mesh")
            if recorded is not None:
                from ray_tpu.parallel.mesh import MeshSpec
                recorded = MeshSpec.from_dict(recorded)
                if mesh is not None and not reshard:
                    current = _mesh_spec(mesh)
                    if recorded != current:
                        # NOT a fall-back case: every retained
                        # snapshot of this run was written on the same
                        # mesh — walking older ones would just repeat
                        # the mismatch against staler state
                        from ray_tpu.resilience.elastic import \
                            MeshMismatchError
                        raise MeshMismatchError(recorded, current)
            try:
                payload = load_pytree(ckpt.path, name=_STATE_NAME,
                                      target=example)
                if example is not None:
                    _validate_tree(payload, example)
            except Exception as e:  # noqa: BLE001 — fall back, loudly
                print(f"checkpoint restore from {ckpt.path} failed "
                      f"({e!r}); falling back to the previous "
                      "retained snapshot", file=sys.stderr)
                continue
            return {"state": payload["state"],
                    "extras": payload.get("extras", {}),
                    "step": int(metrics.get("step", -1)),
                    "path": ckpt.path,
                    "mesh": recorded,
                    "accum_steps": sidecar.get("accum_steps")}
        return None


def run_train_ckpt_loop(cfg, mesh=None, *, steps: int,
                        batch_size: int = 4, seq_len: int = 32,
                        seed: int = 0,
                        ckpt: Optional[TrainCheckpointer] = None,
                        resume: bool = False,
                        fns: Optional[Dict[str, Callable]] = None,
                        on_step: Optional[Callable[[int], None]] = None
                        ) -> Dict[str, Any]:
    """A checkpointed synthetic-LM training loop — the resume-proof
    driver for tests, ``scratch/r15_ft.py`` and preempted-run recovery.

    Every batch is a pure function of ``(seed, cursor)`` —
    ``synthetic_lm_batch(fold_in(data_key, cursor))`` — so the data
    cursor in the checkpoint extras pins the exact batch sequence: a
    resumed run replays from the snapshot's cursor and its loss
    sequence is **bit-exact** against the uninterrupted run (same
    jitted step, same state bits, same batches).

    ``resume=True`` restores the newest valid snapshot from ``ckpt``
    (corrupt ones fall back, see
    :meth:`TrainCheckpointer.restore_latest`) and continues from its
    cursor; with nothing restorable it starts from scratch.
    ``on_step(cursor)`` is a post-step test hook (kill points).
    """
    import jax

    from ray_tpu.models import training

    if mesh is None:
        from ray_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    fns = fns or training.build_gpt_train(cfg, mesh, telemetry=False)
    state = fns["init_fn"](jax.random.PRNGKey(seed))
    data_key = jax.random.PRNGKey(seed + 1)
    cursor = 0
    restored_from = None
    if resume:
        if ckpt is None:
            raise ValueError("resume=True needs a TrainCheckpointer")
        example = {"state": state,
                   "extras": {"data_cursor": np.asarray(0)}}
        restored = ckpt.restore_latest(example=example, mesh=mesh)
        if restored is not None:
            state = jax.device_put(restored["state"],
                                   fns["state_shardings"])
            cursor = int(restored["extras"]["data_cursor"])
            restored_from = restored["path"]
    start = cursor
    losses: List[float] = []
    step_fn = fns["raw_step_fn"] if "raw_step_fn" in fns \
        else fns["step_fn"]
    while cursor < steps:
        batch = training.synthetic_lm_batch(
            jax.random.fold_in(data_key, cursor), batch_size, seq_len,
            cfg.vocab_size)
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))
        cursor += 1
        if ckpt is not None:
            ckpt.maybe_save(state, step=cursor,
                            extras={"data_cursor": cursor},
                            mesh=mesh,
                            accum_steps=fns.get("accum_steps"))
        if on_step is not None:
            on_step(cursor)
    if ckpt is not None:
        ckpt.flush()
    return {
        "losses": losses,
        "start_step": start,
        "steps_run": cursor - start,
        "restored_from": restored_from,
        "final_step": int(np.asarray(state.step)),
        "checkpoint": (ckpt.telemetry.summary() if ckpt is not None
                       else {"enabled": False}),
    }


def run_train_stream_loop(cfg, mesh=None, *, steps: int,
                          batch_size: int = 4, seq_len: int = 32,
                          seed: int = 0,
                          source=None,
                          ckpt: Optional[TrainCheckpointer] = None,
                          resume: bool = False,
                          fns: Optional[Dict[str, Callable]] = None,
                          on_step: Optional[Callable[[int], None]] = None,
                          loader_kwargs: Optional[Dict[str, Any]] = None
                          ) -> Dict[str, Any]:
    """The r17 acceptance driver: :func:`run_train_ckpt_loop` with a
    **streaming** source instead of the trivial fold-in cursor.

    Batches come from :class:`ray_tpu.data.StreamingLoader` — shard
    readers, sample packing (segment-masked ``[B, S]``), the bounded
    prefetch queue — and every delivered batch carries the
    :class:`~ray_tpu.data.StreamCursor` that regenerates its
    successors.  That cursor (fixed-capacity uint8 image: per-shard
    offsets + packer residue; in-flight prefetched batches replay by
    construction) rides the checkpoint ``extras``, so a run killed at
    any step — including via SIGKILL with reads in flight — resumes
    with a loss sequence float-equal to the uninterrupted run's.

    ``source`` defaults to a :class:`~ray_tpu.data.SyntheticDocs`
    corpus derived from ``seed``; pass any
    :class:`~ray_tpu.data.DocumentSource` for real shards.
    ``loader_kwargs`` forwards to the loader (``readers=``, ``pack=``,
    ``prefetch=``, ``retries=`` ...).
    """
    import jax

    from ray_tpu.data.source import SyntheticDocs
    from ray_tpu.data.stream import StreamCursor, StreamingLoader
    from ray_tpu.models import training

    if mesh is None:
        from ray_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    fns = fns or training.build_gpt_train(cfg, mesh, telemetry=False)
    state = fns["init_fn"](jax.random.PRNGKey(seed))
    if source is None:
        source = SyntheticDocs(seed + 1, num_shards=4,
                               docs_per_shard=256,
                               vocab=cfg.vocab_size,
                               min_len=max(2, seq_len // 8),
                               max_len=max(3, (3 * seq_len) // 4))
    lkw = dict(loader_kwargs or {})
    capacity = lkw.pop("cursor_capacity", None)
    if capacity is None:
        from ray_tpu.data.stream import CURSOR_CAPACITY
        capacity = CURSOR_CAPACITY
    cursor = None
    restored_from = None
    if resume:
        if ckpt is None:
            raise ValueError("resume=True needs a TrainCheckpointer")
        example = {"state": state,
                   "extras": {"data_cursor":
                              np.zeros(capacity, np.uint8)}}
        restored = ckpt.restore_latest(example=example, mesh=mesh)
        if restored is not None:
            state = jax.device_put(restored["state"],
                                   fns["state_shardings"])
            cursor = StreamCursor.from_array(
                restored["extras"]["data_cursor"])
            restored_from = restored["path"]
    start = cursor.batches if cursor is not None else 0
    losses: List[float] = []
    step_fn = fns["raw_step_fn"] if "raw_step_fn" in fns \
        else fns["step_fn"]
    with StreamingLoader(source, batch_size=batch_size,
                         seq_len=seq_len, seed=seed, cursor=cursor,
                         cursor_capacity=capacity, **lkw) as loader:
        step = start
        while step < steps:
            try:
                sb = loader.next()
            except StopIteration:
                # a finite stream (loader_kwargs epochs=) drained
                # early: surface it typed, never as a bare
                # StopIteration (PEP 479 would mangle it inside
                # generators)
                from ray_tpu.data.stream import DataPlaneError
                raise DataPlaneError(
                    f"streaming source drained at batch {step} "
                    f"before the requested {steps} steps")
            state, metrics = step_fn(state, sb.batch)
            losses.append(float(metrics["loss"]))
            step = sb.cursor.batches
            if ckpt is not None:
                ckpt.maybe_save(state, step=step,
                                extras={"data_cursor": sb.cursor_array},
                                mesh=mesh,
                                accum_steps=fns.get("accum_steps"))
            if on_step is not None:
                on_step(step)
        data_summary = loader.telemetry.summary()
    if ckpt is not None:
        ckpt.flush()
    return {
        "losses": losses,
        "start_step": start,
        "steps_run": step - start,
        "restored_from": restored_from,
        "final_step": int(np.asarray(state.step)),
        "data": data_summary,
        "checkpoint": (ckpt.telemetry.summary() if ckpt is not None
                       else {"enabled": False}),
    }
