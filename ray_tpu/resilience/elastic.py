"""Elastic training: survive a mesh that comes back *smaller*.

Every resilience layer before this one (r15 checkpoint/resume, r16
reconciler, r17 stream cursor) silently assumed the cluster that
resumes has the same device count as the one that died.  TPU slice
preemption routinely returns fewer chips — at fleet scale failures are
the steady state (arXiv:2510.20171) — so a production run must
restore an 8-device checkpoint onto 4 devices, keep training with an
**unchanged global batch** (the concurrency envelope that makes pod
training predictable, arXiv:2011.03641), and re-expand when capacity
returns.  Three pieces:

- :func:`reshard_state` — move a :class:`~ray_tpu.models.training.
  TrainState` (live or a checkpoint's host snapshot) onto any mesh
  whose data/model axes divide the leaf shapes: host-materialize,
  validate divisibility leaf-by-leaf (typed :class:`ReshardError`
  naming the first offending leaf/axis), ``jax.device_put`` onto the
  new shardings.  Checkpoints already store full host arrays, so
  cross-mesh restore is placement, not resharding arithmetic.

- **global-batch invariance** — ``build_gpt_train(accum_steps=k)``
  (``models/training.py``) runs the step as ``k`` scanned microbatches
  with f32 grad accumulation and one optimizer update, so an 8->4
  shrink doubles ``k`` instead of halving the global batch: the
  optimization trajectory continues, the per-device activation
  footprint stays put, and the loss/grads match the unaccumulated
  step to reduction order.

- :func:`run_elastic_train_loop` — the supervisor: deterministic
  ``mesh.loss`` / ``mesh.restore`` chaos sites (``util/chaos.py``)
  drive shrink -> degraded-steps -> expand transitions; on loss it
  snapshots (graceful, the eviction-notice model) or falls back to
  the latest retained checkpoint (hard preemption), rebuilds the mesh
  at the surviving size with the accumulation factor scaled to keep
  the global batch, reshards, and **compiles exactly once per
  distinct topology** (repeat shrinks to a seen size hit the builder
  cache; asserted via the jit cache sizes the loop returns).

Why bit-exactness ends at the collective reduction order: a degraded
mesh sums the same per-example gradients over a different device
partition (4 shards of scanned pairs vs 8 shards), and float addition
does not associate — so an 8->4->8 run's loss sequence tracks the
uninterrupted 8-device run only to within accumulated rounding drift.
The *data* sequence, by contrast, is exact: batches are a pure
function of the cursor, and the loop's cursor accounting is asserted
float-free (``tests/test_elastic.py``).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.parallel.mesh import MeshSpec, validate_divisibility
from ray_tpu.resilience.config import resilience_config
from ray_tpu.util import chaos


class ElasticError(RuntimeError):
    """Base for elastic-training failures (typed, never a bare
    assert): the supervisor distinguishes 'this topology cannot work'
    from ordinary step exceptions."""


class MeshMismatchError(ElasticError):
    """A checkpoint written on one mesh was asked to restore onto a
    different one without ``reshard=True`` — restoring silently would
    either crash in XLA or, worse, change the run's sharding story
    without anyone deciding that."""

    def __init__(self, recorded: MeshSpec, current: MeshSpec):
        super().__init__(
            f"checkpoint was written on mesh [{recorded.describe()}] "
            f"but restore targets [{current.describe()}] — pass "
            "reshard=True (restore_latest) / use reshard_state to "
            "move it deliberately")
        self.recorded = recorded
        self.current = current

    def __reduce__(self):
        return (MeshMismatchError, (self.recorded, self.current))


class ReshardError(ElasticError):
    """A state leaf cannot shard evenly onto the target mesh — raised
    before any ``device_put``, naming the first offending leaf, its
    shape, and the axis product that fails to divide it."""


def _leaf_paths(tree) -> List[str]:
    import jax
    leaves_with_path = getattr(jax.tree, "leaves_with_path",
                               jax.tree_util.tree_leaves_with_path)
    keystr = jax.tree_util.keystr
    return [keystr(p) for p, _ in leaves_with_path(tree)]


def _axis_sizes(mesh, entry) -> int:
    """Device count a PartitionSpec entry shards a dim over."""
    import math
    if entry is None:
        return 1
    axes = entry if isinstance(entry, tuple) else (entry,)
    return math.prod(mesh.shape.get(a, 1) for a in axes)


def validate_resharding(state, shardings) -> None:
    """Raise :class:`ReshardError` unless every ``state`` leaf's
    sharded dims divide evenly over the target shardings' mesh axes.
    (``jax.device_put`` onto an uneven NamedSharding fails deep inside
    XLA with a shape error that names neither the leaf nor the axis —
    this is the loud, typed front door.)"""
    import jax
    state_leaves = jax.tree.leaves(state)
    sh_leaves = jax.tree.leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec"))
    if len(state_leaves) != len(sh_leaves):
        raise ReshardError(
            f"state has {len(state_leaves)} leaves but the target "
            f"shardings have {len(sh_leaves)} — not the same "
            "TrainState structure")
    paths = _leaf_paths(state)
    for path, leaf, sh in zip(paths, state_leaves, sh_leaves):
        shape = np.shape(leaf)
        spec = getattr(sh, "spec", None)
        mesh = getattr(sh, "mesh", None)
        if spec is None or mesh is None:
            continue                      # replicated / opaque: free
        for dim, entry in enumerate(spec):
            if dim >= len(shape):
                break
            div = _axis_sizes(mesh, entry)
            if div > 1 and shape[dim] % div:
                raise ReshardError(
                    f"state leaf {path} dim {dim} (size "
                    f"{shape[dim]}) does not divide over mesh axes "
                    f"{entry} (product {div}) — this state cannot "
                    f"reshard onto [{MeshSpec.from_mesh(mesh).describe()}]")


def host_state(state):
    """Device pytree -> host numpy pytree (a consistent cut: blocks
    until every leaf's producer is done — the same barrier the async
    checkpointer snapshots behind).  One implementation, shared with
    ``TrainCheckpointer`` — its np.asarray-not-ascontiguousarray
    constraint (0-d step counter must stay 0-d) is load-bearing for
    restore validation."""
    from ray_tpu.resilience.checkpoint import _host_tree
    return _host_tree(state)


def reshard_state(state, shardings):
    """Move ``state`` (device or host pytree) onto the mesh described
    by ``shardings`` (a matching pytree of ``NamedSharding`` — e.g.
    ``build_gpt_train(...)['state_shardings']`` for the new mesh).

    The state is host-materialized first: cross-mesh ``device_put`` of
    already-committed shards would otherwise resolve placement against
    the *old* mesh's devices, and a genuinely lost device must not be
    touched at all.  Divisibility is validated up front
    (:func:`validate_resharding`) so an impossible target fails as a
    typed :class:`ReshardError`, not an XLA internal error."""
    import jax
    host = host_state(state)
    validate_resharding(host, shardings)
    return jax.device_put(host, shardings)


# ------------------------------------------------------------- the loop
def _shrink_target(current: int, min_devices: int) -> int:
    """Surviving size after a mesh-loss event: half the mesh, floored
    at ``min_devices`` (the host-sim stand-in for 'whatever subset the
    platform reports alive')."""
    return max(min_devices, current // 2)


def run_elastic_train_loop(cfg, *, steps: int,
                           batch_size: int = 8, seq_len: int = 32,
                           seed: int = 0,
                           axis: str = "fsdp",
                           devices=None,
                           degraded_devices: Optional[int] = None,
                           accum_steps: int = 1,
                           optimizer=None,
                           ckpt=None,
                           graceful: Optional[bool] = None,
                           min_devices: Optional[int] = None,
                           telemetry: Optional[bool] = None,
                           straggler=None,
                           on_step: Optional[Callable[[int], None]] = None,
                           topologies: Optional[Dict[int, Dict[str, Any]]]
                           = None) -> Dict[str, Any]:
    """A synthetic-LM training loop that survives mesh shrink/expand —
    the elastic acceptance driver for tests, ``scratch/r18_elastic.py``
    and degraded-restore recovery.

    Topology events come from the deterministic chaos sites (armed via
    ``RAY_TPU_FAULTS`` or :func:`~ray_tpu.util.chaos.install_faults`;
    each site counts one hit per step):

    - ``mesh.loss`` — the mesh loses devices: the loop snapshots the
      state (``graceful=True``, the eviction-notice model — zero lost
      steps) or restores the latest retained checkpoint (hard loss;
      the cursor rolls back with it, bounded by the cadence), rebuilds
      at ``degraded_devices`` (default: half, floored at
      ``min_devices``) with ``accum_steps`` scaled by the shrink
      factor so the **global batch is unchanged**, reshards, and keeps
      training.
    - ``mesh.restore`` — capacity returned: same dance back to the
      full mesh, accumulation scaled back down.
    - ``mesh.step`` — gray failure (r19): a ``:delay=S`` window
      stretches the step wall (a straggling host gates the
      synchronous step).  Nothing is lost — but the run is paying the
      straggler's pace.  With a straggler supervisor armed
      (``straggler=True`` / a :class:`~ray_tpu.resilience.straggler.
      StragglerSupervisor` / ``RAY_TPU_STRAGGLER_FACTOR`` > 0), a
      sustained straggle is converted into the same graceful
      shrink a ``mesh.loss`` takes (snapshot -> rebuild at the
      degraded size with the global batch unchanged -> reshard), so
      the run trades the straggler's capacity for its speed;
      expansion still rides ``mesh.restore``.  A straggle already at
      the ``min_devices`` floor is logged and ridden out — unlike a
      declared device loss, the state is intact, so training on is
      correct (just slow).

    Every batch is a pure function of ``(seed, cursor)`` (the
    ``run_train_ckpt_loop`` contract), so the returned
    ``batch_cursors`` list *is* the consumed-data accounting: two runs
    with equal lists trained on identical document sequences, exactly.
    Compiled steps are cached per device count — ``compile_counts``
    reports each topology's jit cache size (the acceptance invariant:
    exactly 1 per distinct mesh, repeat shrinks compile nothing).
    ``topologies``: an externally-held cache dict, shared across runs
    of identical ``(cfg, geometry, optimizer)`` so tests and A/B
    drivers pay each topology's compile once per process (the r15/r17
    shared-fixture precedent); ``builds`` then lists only the
    topologies THIS run had to build.
    """
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.telemetry.config import TelemetryConfig
    from ray_tpu.telemetry.elastic import ElasticTelemetry

    rcfg = resilience_config()
    if graceful is None:
        graceful = rcfg.elastic_graceful
    if min_devices is None:
        min_devices = rcfg.elastic_min_devices
    devices = list(devices if devices is not None else jax.devices())
    n_full = len(devices)
    if degraded_devices is None:
        degraded_devices = _shrink_target(n_full, min_devices)
    if degraded_devices < min_devices:
        raise ElasticError(
            f"degraded_devices={degraded_devices} is below "
            f"min_devices={min_devices} "
            "(RAY_TPU_ELASTIC_MIN_DEVICES) — a loss this deep is "
            "declared fatal, not a target")
    tel_config = (TelemetryConfig(enabled=bool(telemetry))
                  if isinstance(telemetry, bool) else None)
    tel = ElasticTelemetry(config=tel_config)
    tx = optimizer or training.default_optimizer()

    from ray_tpu.resilience.straggler import StragglerSupervisor
    if isinstance(straggler, StragglerSupervisor):
        watch = straggler
    elif straggler is None:
        watch = StragglerSupervisor()      # env-armed (factor 0 = off)
    elif straggler:
        sfactor = rcfg.straggler_factor or 3.0
        watch = StragglerSupervisor(factor=sfactor)
    else:
        watch = StragglerSupervisor(factor=0.0)

    if topologies is None:
        topologies = {}
    builds: List[int] = []

    def topology(n: int) -> Dict[str, Any]:
        if n in topologies:
            return topologies[n]
        if n < 1 or n > n_full:
            raise ElasticError(f"cannot build a {n}-device mesh from "
                               f"{n_full} devices")
        if n_full % n:
            raise ElasticError(
                f"surviving device count {n} does not divide the full "
                f"mesh ({n_full}): the accumulation factor that keeps "
                "the global batch would not be whole")
        k = accum_steps * (n_full // n)
        mesh = make_mesh(**{axis: n}, devices=devices[:n])
        validate_divisibility(mesh, batch=batch_size, accum_steps=k)
        fns = training.build_gpt_train(cfg, mesh, optimizer=tx,
                                       accum_steps=k, telemetry=False)
        topologies[n] = {"mesh": mesh, "fns": fns, "n": n,
                         "spec": MeshSpec.from_mesh(mesh),
                         "accum_steps": k}
        builds.append(n)
        return topologies[n]

    topo = topology(n_full)
    state = topo["fns"]["init_fn"](jax.random.PRNGKey(seed))
    data_key = jax.random.PRNGKey(seed + 1)
    cursor = 0
    tel.record_mesh(n_full)

    losses: List[float] = []
    batch_cursors: List[int] = []
    transitions: List[Dict[str, Any]] = []
    straggler_events: List[int] = []

    def transition(kind: str, target: int, cause: str = "fault"):
        nonlocal state, topo, cursor
        src = topo["n"]
        if target == src:
            return                          # already there: no-op
        t0 = time.monotonic()
        if kind == "shrink" and not graceful and cause != "straggler":
            if ckpt is None:
                raise ElasticError(
                    "hard mesh loss (graceful=False) needs a "
                    "TrainCheckpointer to fall back to")
            # the live state is lost with the mesh, but its SHAPES are
            # the restore target (orbax needs a typed example to give
            # back the TrainState structure, not a raw dict)
            example = {"state": state,
                       "extras": {"data_cursor": np.asarray(0)}}
            restored = ckpt.restore_latest(example=example,
                                           reshard=True)
            if restored is None:
                raise ElasticError(
                    "hard mesh loss with nothing restorable: the run "
                    "is lost (checkpoint before arming mesh.loss)")
            snapshot = restored["state"]
            cursor = int(np.asarray(restored["extras"]["data_cursor"]))
        else:
            # graceful: the eviction notice arrived — final snapshot
            # off the dying mesh (host copy only; the old devices are
            # never touched again after this line)
            snapshot = host_state(state)
        new = topology(target)
        state = reshard_state(snapshot, new["fns"]["state_shardings"])
        dt = time.monotonic() - t0
        topo = new
        transitions.append({"kind": kind, "step": cursor,
                            "from": src, "to": target,
                            "cause": cause,
                            "reshard_s": round(dt, 6)})
        tel.record_transition(kind, dt, n_devices=target)
        # the new topology has a new normal step wall: a straggler
        # baseline carried across it would misfire
        watch.reset()

    while cursor < steps:
        if chaos.should_fire("mesh.loss"):
            target = (_shrink_target(topo["n"], min_devices)
                      if degraded_devices >= topo["n"]
                      else degraded_devices)
            if target >= topo["n"]:
                # already at the floor: the documented contract is
                # that a loss below RAY_TPU_ELASTIC_MIN_DEVICES is
                # FATAL — a 1-device "fleet" may be worse than waiting
                # for quota, and silently ignoring a declared device
                # loss would train on state the event said is gone
                raise ElasticError(
                    f"mesh.loss at the min_devices floor: the "
                    f"{topo['n']}-device mesh cannot shrink below "
                    f"min_devices={min_devices} "
                    "(RAY_TPU_ELASTIC_MIN_DEVICES) — the loss is "
                    "fatal; resume from the latest checkpoint when "
                    "capacity returns")
            transition("shrink", target)
        if chaos.should_fire("mesh.restore"):
            transition("expand", n_full)
        batch = training.synthetic_lm_batch(
            jax.random.fold_in(data_key, cursor), batch_size, seq_len,
            cfg.vocab_size)
        batch_cursors.append(cursor)
        t_step = time.monotonic()
        # the mesh.step slowdown site stretches exactly the window the
        # straggler supervisor watches — an injected gray failure is
        # indistinguishable from a genuinely straggling host
        chaos.maybe_fail("mesh.step")
        state, metrics = topo["fns"]["step_fn"](state, batch)
        losses.append(float(metrics["loss"]))   # blocks: the wall is real
        step_wall = time.monotonic() - t_step
        cursor += 1
        # per-tier baseline: a DCN-crossing step is legitimately
        # slower than an ICI-only one, so each tier judges its own
        step_tier = ("dcn" if topo["mesh"].shape.get("dcn", 1) > 1
                     else "ici")
        if watch.observe(step_wall, tier=step_tier):
            straggler_events.append(cursor - 1)
            tel.record_straggler()
            target = (_shrink_target(topo["n"], min_devices)
                      if degraded_devices >= topo["n"]
                      else degraded_devices)
            if target < topo["n"]:
                # degraded-mesh event via the r18 machinery: ALWAYS a
                # graceful snapshot — unlike a declared loss, the
                # state is intact, the straggler just taxes it
                transition("shrink", target, cause="straggler")
            # at the min_devices floor there is nothing to shed:
            # intact state, so training on (slow) is correct — the
            # event is still counted for the operator
        if ckpt is not None:
            ckpt.maybe_save(state, step=cursor,
                            extras={"data_cursor": cursor},
                            mesh=topo["mesh"],
                            accum_steps=topo["accum_steps"])
        if on_step is not None:
            on_step(cursor)
    if ckpt is not None:
        ckpt.flush()

    compile_counts = {
        n: t["fns"]["step_fn"]._cache_size()
        for n, t in topologies.items()
        if hasattr(t["fns"]["step_fn"], "_cache_size")}
    return {
        "losses": losses,
        "batch_cursors": batch_cursors,
        "transitions": transitions,
        "straggler_events": straggler_events,
        "builds": builds,
        "compile_counts": compile_counts,
        "final_step": int(np.asarray(state.step)),
        "final_devices": topo["n"],
        "accum_steps": topo["accum_steps"],
        "elastic": tel.summary(),
        "checkpoint": (ckpt.telemetry.summary() if ckpt is not None
                       else {"enabled": False}),
    }
