"""Resilience env knobs — the single home for checkpoint/recovery config.

Follows the ``infer_config()`` / ``rl_config()`` precedent: one frozen
dataclass resolved from the environment once, ``refresh=True`` for
tests and A/B drivers that flip flags after import.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Checkpoint/recovery knobs, resolved once from the environment.

    - ``RAY_TPU_CKPT_EVERY`` (default ``0`` = off): training steps
      between async TrainState snapshots.  The snapshot (device->host
      copy) runs on the training thread; the disk write runs on the
      checkpointer's background thread, off the critical path.
    - ``RAY_TPU_CKPT_DIR`` (default unset): checkpoint directory.  A
      :class:`~ray_tpu.resilience.checkpoint.TrainCheckpointer` built
      without an explicit directory uses this; with neither set,
      checkpointing is off.
    - ``RAY_TPU_CKPT_KEEP`` (default ``3``): retained snapshots —
      retention rides ``train/checkpoint_manager.py`` (newest-first;
      the corrupt-restore fallback walks these in order).
    - ``RAY_TPU_ELASTIC_MIN_DEVICES`` (default ``1``): the smallest
      mesh :func:`~ray_tpu.resilience.elastic.run_elastic_train_loop`
      will degrade to on a ``mesh.loss`` event — below it the loss is
      fatal (a 1-device "fleet" may be worse than waiting for quota).
    - ``RAY_TPU_ELASTIC_GRACEFUL`` (default ``1``): whether a mesh
      loss gets a final host snapshot (the TPU eviction-notice model:
      zero lost steps) or must restore from the latest retained
      checkpoint (hard preemption: lost work bounded by the cadence).
    - ``RAY_TPU_STRAGGLER_FACTOR`` (default ``0`` = off): straggler
      threshold — a train step slower than this multiple of the
      rolling-median baseline counts as slow
      (:class:`~ray_tpu.resilience.straggler.StragglerSupervisor`).
    - ``RAY_TPU_STRAGGLER_DWELL`` (default ``3``): consecutive slow
      steps before a straggle event fires — a cold compile or one GC
      pause is a blip, never a shrink.
    - ``RAY_TPU_STRAGGLER_WINDOW`` (default ``16``): rolling-baseline
      window in accepted (non-slow) step samples.
    """
    ckpt_every: int = 0
    ckpt_dir: Optional[str] = None
    ckpt_keep: int = 3
    elastic_min_devices: int = 1
    elastic_graceful: bool = True
    straggler_factor: float = 0.0
    straggler_dwell: int = 3
    straggler_window: int = 16


_CONFIG: Optional[ResilienceConfig] = None


def resilience_config(refresh: bool = False) -> ResilienceConfig:
    """The process-wide :class:`ResilienceConfig` (env read once)."""
    global _CONFIG
    if _CONFIG is None or refresh:
        env = os.environ.get
        every = int(env("RAY_TPU_CKPT_EVERY", "0"))
        if every < 0:
            print(f"RAY_TPU_CKPT_EVERY={every} negative; using 0 "
                  "(checkpointing off)", file=sys.stderr)
            every = 0
        keep = int(env("RAY_TPU_CKPT_KEEP", "3"))
        if keep < 1:
            print(f"RAY_TPU_CKPT_KEEP={keep} must be >= 1 (resume "
                  "needs at least the latest snapshot); using 1",
                  file=sys.stderr)
            keep = 1
        min_dev = int(env("RAY_TPU_ELASTIC_MIN_DEVICES", "1"))
        if min_dev < 1:
            print(f"RAY_TPU_ELASTIC_MIN_DEVICES={min_dev} must be "
                  ">= 1; using 1", file=sys.stderr)
            min_dev = 1
        factor = float(env("RAY_TPU_STRAGGLER_FACTOR", "0"))
        if factor < 0:
            print(f"RAY_TPU_STRAGGLER_FACTOR={factor} negative; "
                  "using 0 (straggler detection off)", file=sys.stderr)
            factor = 0.0
        dwell = int(env("RAY_TPU_STRAGGLER_DWELL", "3"))
        if dwell < 1:
            print(f"RAY_TPU_STRAGGLER_DWELL={dwell} must be >= 1; "
                  "using 1", file=sys.stderr)
            dwell = 1
        window = int(env("RAY_TPU_STRAGGLER_WINDOW", "16"))
        if window < 3:
            print(f"RAY_TPU_STRAGGLER_WINDOW={window} must be >= 3 "
                  "(the baseline is a median); using 3",
                  file=sys.stderr)
            window = 3
        _CONFIG = ResilienceConfig(
            ckpt_every=every,
            ckpt_dir=env("RAY_TPU_CKPT_DIR") or None,
            ckpt_keep=keep,
            elastic_min_devices=min_dev,
            elastic_graceful=env("RAY_TPU_ELASTIC_GRACEFUL", "1")
            not in ("0", "false", "False"),
            straggler_factor=factor,
            straggler_dwell=dwell,
            straggler_window=window,
        )
    return _CONFIG
