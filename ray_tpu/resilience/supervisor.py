"""Supervised actor/learner RL loop: survive deaths mid-training.

``run_rl_loop`` (r14) is the fair-weather driver: any actor or learner
failure loses the whole run.  This module is the Podracer answer
(arXiv:2104.06272 — preemption is normal, checkpoint/restart is the
recovery path) applied to the RL subsystem:

- **actor supervision** — every rollout is health-checked by outcome;
  a dead actor (engine fault, injected ``rl.rollout`` kill) is
  replaced by a fresh :class:`~ray_tpu.rl.rollout.RolloutActor`
  re-seeded from the **latest** :class:`~ray_tpu.rl.replay.WeightStore`
  version.  Replacements share the fleet's executable cache, so a
  restart compiles **nothing** (asserted by counters in the chaos
  acceptance test) — restart cost is engine construction + one
  device_put, not XLA.
- **learner checkpointing** — every ``ckpt_every`` learner steps the
  full learner :class:`~ray_tpu.models.training.TrainState` (params +
  opt state + step), the published version and the rollout-seed cursor
  snapshot through the async
  :class:`~ray_tpu.resilience.checkpoint.TrainCheckpointer`.  A
  learner death (injected ``rl.learner``) restores the newest valid
  snapshot in place and **republishes** under a fresh version, so
  actors resync and stale in-queue batches age out through the
  existing ``max_lag`` bound.
- **loop resume** — a killed *process* reruns with ``resume=True``:
  learner state, step counter and seed cursor restore from the
  checkpoint; lost work is bounded by (checkpoint interval + one
  :class:`~ray_tpu.rl.replay.ReplayQueue` of trajectories), never the
  run.
- **publish supervision** — a failed weight publication (injected
  ``rl.publish``) is counted and skipped; actors continue on the
  previous consistent version.

Wait-policy rejections here are non-blocking (held batch + retry) —
the driver is single-threaded, so a timed put would stall waiting for
its own consumer.  The timed put (``RAY_TPU_RL_PUT_TIMEOUT`` ->
:class:`~ray_tpu.rl.replay.ReplayPutTimeout`, counted as
backpressure) is the contract for actors whose learner pops from
another thread/process — it bounds how long a producer can block on a
dead learner.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.rl.config import RLConfig, rl_config
from ray_tpu.rl.learner import InProcessLearner
from ray_tpu.rl.replay import (ReplayPutTimeout, ReplayQueue,
                               WeightStore)
from ray_tpu.rl.reward import target_token_reward
from ray_tpu.rl.rollout import RolloutActor
from ray_tpu.resilience.checkpoint import TrainCheckpointer
from ray_tpu.util import chaos


def _drain_engine(engine) -> None:
    """Best-effort retire of everything a (possibly dying) engine
    holds.  Never raises — the engine may be the thing that just
    failed."""
    try:
        engine.drain_requests()
    except Exception:  # noqa: BLE001 — cleanup of a broken engine
        pass


def _put_with_backpressure(queue: ReplayQueue, batch, *, tel) -> bool:
    """One queue put under the supervised policy: a ``wait``-policy
    rejection counts as backpressure and returns False so the caller
    holds the batch.

    Deliberately NON-blocking (no ``RAY_TPU_RL_PUT_TIMEOUT`` here):
    this driver runs producer and consumer on one thread, so a timed
    put would wait for a pop that cannot happen until it returns — a
    guaranteed full-timeout stall per backpressured actor.  The timed
    put (:class:`~ray_tpu.rl.replay.ReplayPutTimeout`) is for actors
    whose learner pops from another thread/process."""
    try:
        if queue.put(batch):
            return True
    except ReplayPutTimeout:       # pragma: no cover — defensive
        pass
    tel.record_backpressure()
    return False


def run_supervised_rl_loop(cfg, *, steps: int,
                           rlcfg: Optional[RLConfig] = None,
                           reward_fn: Optional[Callable] = None,
                           prompt: Optional[Sequence[int]] = None,
                           prompt_len: int = 4,
                           eos_token: Optional[int] = None,
                           seed: int = 0,
                           lr: float = 1e-3,
                           mesh=None,
                           optimizer=None,
                           ckpt: Optional[TrainCheckpointer] = None,
                           ckpt_every: Optional[int] = None,
                           resume: bool = False,
                           max_actor_restarts: int = 8,
                           max_learner_restarts: int = 3,
                           engine_kwargs: Optional[Dict[str, Any]] = None,
                           learner_fns: Optional[Dict[str, Any]] = None,
                           telemetry: Optional[bool] = None
                           ) -> Dict[str, Any]:
    """``run_rl_loop`` semantics under supervision (in-process learner).

    Same fixed-seed determinism contract as the r14 loop *until the
    first fault*: an undisturbed supervised run reproduces
    ``run_rl_loop``'s trajectories exactly (same seeds, same order).
    After a fault the trajectories diverge by construction — recovery
    is judged on the reward criterion (final-third mean within
    tolerance of an uninterrupted run), not bitwise.

    ``ckpt``/``ckpt_every`` arm learner checkpointing (``ckpt_every``
    defaults to the checkpointer's own ``RAY_TPU_CKPT_EVERY`` cadence);
    ``resume=True`` restores the newest valid snapshot before the
    first rollout.  ``max_learner_restarts=0`` disables in-place
    learner recovery: the death propagates, and the caller reruns with
    ``resume=True`` (the killed-loop path).

    Returns the ``run_rl_loop`` result dict plus ``actor_restarts``,
    ``learner_restarts``, ``publish_failures``, ``resumed_from`` and
    the ``checkpoint`` telemetry block.
    """
    rlcfg = rlcfg or rl_config()
    rng = np.random.RandomState(seed)
    if prompt is None:
        prompt = [int(t) for t in
                  rng.randint(0, cfg.vocab_size, prompt_len)]
    prompts = [list(prompt)] * rlcfg.batch
    seq_len = len(prompt) + rlcfg.horizon
    if reward_fn is None:
        target = int(rng.randint(0, cfg.vocab_size))
        reward_fn = target_token_reward(
            target, length_penalty=1.0 / max(rlcfg.horizon, 1),
            eos_token=eos_token)

    from ray_tpu.telemetry.config import TelemetryConfig
    from ray_tpu.telemetry.rl import RLTelemetry
    tel = RLTelemetry(config=None if telemetry is None else
                      TelemetryConfig(enabled=bool(telemetry)))

    learner = InProcessLearner(cfg, mesh=mesh, baseline=rlcfg.baseline,
                               lr=lr, optimizer=optimizer, seed=seed,
                               fns=learner_fns)
    store = WeightStore(use_object_store=False)
    # put_timeout pinned to 0 — single-threaded driver, see
    # _put_with_backpressure
    queue = ReplayQueue(rlcfg.queue, max_lag=rlcfg.max_lag,
                        overflow=rlcfg.overflow, put_timeout=0)
    ckpt_every = (ckpt.every if ckpt is not None and ckpt_every is None
                  else (ckpt_every or 0))

    learner_steps = 0
    rollout_seed = seed * 1_000_003
    publish_failures = 0
    resumed_from = None

    def checkpoint_now():
        if ckpt is None:
            return
        ckpt.save(learner.state_host(), step=learner_steps,
                  extras={"version": store.version,
                          "learner_steps": learner_steps,
                          "rollout_seed": rollout_seed})

    def restore_learner() -> bool:
        """Newest valid snapshot -> learner + counters; False when the
        directory holds nothing usable (fresh start)."""
        nonlocal learner_steps, rollout_seed, resumed_from
        if ckpt is None:
            return False
        example = {"state": learner.state_host(),
                   "extras": {"version": np.asarray(0),
                              "learner_steps": np.asarray(0),
                              "rollout_seed": np.asarray(0)}}
        restored = ckpt.restore_latest(example=example)
        if restored is None:
            return False
        learner.load_state(restored["state"])
        learner_steps = int(restored["extras"]["learner_steps"])
        rollout_seed = int(restored["extras"]["rollout_seed"])
        resumed_from = restored["path"]
        return True

    def publish(must: bool = False) -> bool:
        """One supervised publication; a failure (injected or real) is
        fatal only when ``must`` (the seed publish — actors cannot
        start without version 1)."""
        nonlocal publish_failures
        t0 = time.monotonic()
        try:
            version = store.publish(learner.params_host())
        except Exception as e:  # noqa: BLE001 — supervised: skip one
            if must:
                raise
            publish_failures += 1
            print(f"weight publish failed ({e!r}); actors stay on "
                  f"version {store.version}", file=sys.stderr)
            return False
        tel.record_publish(time.monotonic() - t0, version=version)
        return True

    if resume:
        restore_learner()
    publish(must=True)           # seeds actors (fresh or restored)
    checkpoint_now()             # in-place learner recovery needs >= 1
    # history/reward_curve index THIS call's counted steps; a resumed
    # run starts its records at `base_steps`, so mid-loop rollbacks
    # must truncate relative to it, not to the absolute step counter
    base_steps = learner_steps
    shared_exec: Dict[Any, Any] = {}
    ekw = dict(engine_kwargs or {})
    ekw.setdefault("executable_cache", shared_exec)
    ekw.setdefault("telemetry", False)

    def spawn_actor(aid: int) -> RolloutActor:
        version, params = store.latest()
        actor = RolloutActor(cfg, params, actor_id=aid,
                             temperature=rlcfg.temperature,
                             eos_token=eos_token, engine_kwargs=ekw)
        actor.engine.param_version = version
        return actor

    actors = [spawn_actor(i) for i in range(rlcfg.actors)]
    actor_restarts = 0
    learner_restarts = 0
    # per-actor compile counters at spawn time tell the acceptance
    # test which engines were born after the cache warmed
    restart_compiles: List[Dict[str, int]] = []

    history: List[Dict[str, float]] = []
    reward_curve: List[float] = []
    pending: Dict[int, Any] = {}
    try:
        while learner_steps < steps:
            # ---- held batches first (the r14 no-starvation order)
            for aid in list(pending):
                if _put_with_backpressure(queue, pending[aid],
                                          tel=tel):
                    del pending[aid]
            # ---- actor side, supervised: a rollout that raises kills
            # only its actor; the replacement syncs to the latest
            # publication and takes over the same slot in the fleet
            for i, actor in enumerate(actors):
                if actor.actor_id in pending:
                    continue
                if actor.param_version != store.version:
                    version, params = store.latest()
                    actor.sync(version, params)
                rollout_seed += rlcfg.batch
                try:
                    batch = actor.rollout(prompts,
                                          horizon=rlcfg.horizon,
                                          seq_len=seq_len,
                                          reward_fn=reward_fn,
                                          seed=rollout_seed)
                except Exception as e:  # noqa: BLE001 — supervise
                    if actor_restarts >= max_actor_restarts:
                        raise
                    actor_restarts += 1
                    tel.record_actor_restart()
                    print(f"rollout actor {actor.actor_id} died "
                          f"({e!r}); restarting from version "
                          f"{store.version}", file=sys.stderr)
                    _drain_engine(actor.engine)
                    # leak check NOW (the same clean-idle invariant
                    # the shutdown path asserts), then drop the
                    # engine: keeping dead engines around would pin
                    # their device params + KV arrays (a whole replica
                    # of HBM each) for the rest of the run
                    if not actor.idle():
                        raise RuntimeError(
                            f"dead rollout engine {actor.actor_id} did "
                            "not drain clean (slots/pages still held) "
                            "— the recovery path broke the allocator "
                            "invariants") from e
                    actors[i] = spawn_actor(actor.actor_id)
                    restart_compiles.append(
                        dict(actors[i].engine.compile_counts))
                    continue        # the fleet moves on this round
                tel.record_rollout(batch.wall_s,
                                   tokens=batch.gen_tokens,
                                   param_version=batch.param_version)
                if not _put_with_backpressure(queue, batch, tel=tel):
                    pending[actor.actor_id] = batch
            # ---- learner side, supervised: drain what is fresh
            while learner_steps < steps:
                batch = queue.pop(store.version)
                if batch is None:
                    break
                lag = store.version - batch.param_version
                t0 = time.monotonic()
                try:
                    chaos.maybe_fail("rl.learner")
                    metrics = learner.update(batch.as_learner_batch())
                except Exception as e:  # noqa: BLE001 — supervise
                    if ckpt is None or \
                            learner_restarts >= max_learner_restarts:
                        raise
                    learner_restarts += 1
                    tel.record_learner_restart()
                    print(f"learner died ({e!r}); restoring from its "
                          "checkpoint and republishing",
                          file=sys.stderr)
                    if not restore_learner():
                        raise
                    # roll the records back with the learner so
                    # history[i] / reward_curve[i] stays "the i-th
                    # counted step of THIS call" — without this the
                    # re-run steps would be double-counted and the
                    # curve's indices would stop meaning anything
                    # (clamped: a corrupt-newest fallback can restore
                    # a snapshot older than this call's starting point,
                    # which invalidates every record of this call)
                    keep = max(learner_steps - base_steps, 0)
                    del history[keep:]
                    del reward_curve[keep:]
                    publish(must=True)   # fresh version: actors resync
                    break                # back to the rollout side
                tel.record_learner_step(time.monotonic() - t0,
                                        version_lag=lag)
                learner_steps += 1
                metrics["rollout_reward_mean"] = float(
                    np.mean(batch.rewards))
                metrics["param_version_lag"] = float(lag)
                history.append(metrics)
                reward_curve.append(metrics["rollout_reward_mean"])
                if learner_steps % rlcfg.publish_every == 0:
                    publish()
                if ckpt_every and learner_steps % ckpt_every == 0:
                    checkpoint_now()
    finally:
        leftover = queue.drain() + list(pending.values())
        if ckpt is not None:
            ckpt.flush()
    tel.record_queue_counters(drops_stale=queue.drops_stale,
                              drops_overflow=queue.drops_overflow)
    leaked = [a.actor_id for a in actors if not a.idle()]
    if leaked:
        raise RuntimeError(f"rollout engines {leaked} did not drain "
                           "clean at shutdown (slots/pages still held)")
    return {
        "steps": learner_steps,
        "history": history,
        "reward_curve": reward_curve,
        "leftover_batches": len(leftover),
        "drops_stale": queue.drops_stale,
        "drops_overflow": queue.drops_overflow,
        "backpressure_rejections": queue.backpressure_rejections,
        "param_version": store.version,
        "publishes": store.publish_count,
        "publish_failures": publish_failures,
        "actor_restarts": actor_restarts,
        "learner_restarts": learner_restarts,
        "restart_compiles": restart_compiles,
        "resumed_from": resumed_from,
        "telemetry": tel.summary(),
        "checkpoint": (ckpt.telemetry.summary() if ckpt is not None
                       else {"enabled": False}),
        "engine_stats": [a.engine.stats() for a in actors],
        "actors": [a.engine for a in actors],
        "learner": learner,
    }
