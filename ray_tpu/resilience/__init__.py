"""``ray_tpu.resilience`` — supervision and recovery for the ML stack.

TPU fleets are preemptible and serving fleets shed replicas; this
package is the layer that turns those deaths from run-killers into
bounded hiccups, spanning all four workloads built in r06–r14:

- **train** — async bit-exact checkpoint/resume
  (:class:`~ray_tpu.resilience.checkpoint.TrainCheckpointer`,
  :func:`~ray_tpu.resilience.checkpoint.run_train_ckpt_loop`):
  snapshots off the critical path, orbax/npz + checkpoint-manager
  retention, corrupt snapshots fall back loudly.  With a streaming
  source (:func:`~ray_tpu.resilience.checkpoint.
  run_train_stream_loop`, r17) the data-plane cursor rides the same
  extras — resume is float-equal even with reader deaths and
  SIGKILLs mid-stream.
- **elastic** (r18) — the cluster that comes back may be *smaller*:
  cross-mesh checkpoint restore (:func:`~ray_tpu.resilience.elastic.
  reshard_state`, the mesh/accum sidecar +
  :class:`~ray_tpu.resilience.elastic.MeshMismatchError` refusal),
  global-batch-invariant gradient accumulation
  (``build_gpt_train(accum_steps=)``), and the shrink/expand
  supervisor :func:`~ray_tpu.resilience.elastic.
  run_elastic_train_loop` driven by the ``mesh.loss`` /
  ``mesh.restore`` chaos sites.
- **RL** — the supervised actor/learner loop
  (:func:`~ray_tpu.resilience.supervisor.run_supervised_rl_loop`):
  dead rollout actors restart from the latest published weights with
  zero recompiles, the learner checkpoints and restores in place, a
  killed loop resumes with bounded lost work.
- **inference/serve** — per-request TTFT/total deadlines (typed
  :class:`~ray_tpu.inference.scheduler.DeadlineExceededError`,
  everything released on expiry), the
  :class:`~ray_tpu.resilience.watchdog.EngineWatchdog` wedge
  detector, and graceful deployment drain.
- **proof** — all of the above is exercised by the deterministic
  fault-injection plan in :mod:`ray_tpu.util.chaos`
  (``RAY_TPU_FAULTS``), not just unit-tested.

Config via ``RAY_TPU_CKPT_*`` (:func:`resilience_config`); the
deadline/watchdog knobs live with the engine's
(``RAY_TPU_INFER_*``).
"""

from ray_tpu.resilience.checkpoint import (TrainCheckpointer,  # noqa: F401
                                           run_train_ckpt_loop,
                                           run_train_stream_loop)
from ray_tpu.resilience.config import (ResilienceConfig,  # noqa: F401
                                       resilience_config)
from ray_tpu.resilience.elastic import (ElasticError,  # noqa: F401
                                        MeshMismatchError,
                                        ReshardError,
                                        reshard_state,
                                        run_elastic_train_loop)
from ray_tpu.resilience.straggler import StragglerSupervisor  # noqa: F401
from ray_tpu.resilience.supervisor import run_supervised_rl_loop  # noqa: F401
from ray_tpu.resilience.watchdog import EngineWatchdog  # noqa: F401

__all__ = [
    "ResilienceConfig", "resilience_config",
    "TrainCheckpointer", "run_train_ckpt_loop",
    "run_train_stream_loop",
    "run_supervised_rl_loop",
    "ElasticError", "MeshMismatchError", "ReshardError",
    "reshard_state", "run_elastic_train_loop",
    "StragglerSupervisor",
    "EngineWatchdog",
]
