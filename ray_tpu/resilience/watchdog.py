"""Engine watchdog: detect a wedged step loop.

A serving replica's failure mode that deadlines cannot catch: the pump
stops calling ``engine.step()`` (event-loop starvation, a dead
executor thread) or a step call itself hangs (device wedge, a stuck
host collective).  Every request then ages out silently — the queue
looks "busy" forever.  The watchdog is the liveness cross-check: the
engine stamps ``ticks``/``last_tick_ts`` at the end of every completed
``step()``, and a background thread declares a **wedge** when the
engine has work pending but neither stamp has moved for ``timeout_s``.

Detection is deliberately separated from reaction: the default
``on_wedge`` warns on stderr and counts (``wedges``, surfaced through
the deployment's telemetry summary) — whether to drain, restart the
replica or page someone is policy the caller injects.  One wedge fires
once per stall episode; progress re-arms it.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Optional


class EngineWatchdog:
    """Liveness monitor over one :class:`~ray_tpu.inference.engine.
    InferenceEngine` (anything with ``has_work()``/``ticks``/
    ``last_tick_ts`` quacks).

    ``timeout_s``: stall budget — has-work with no completed tick for
    this long is a wedge.  ``on_wedge(engine)`` runs on the watchdog
    thread, once per episode.  Context-manager friendly.
    """

    def __init__(self, engine, *, timeout_s: float,
                 poll_s: Optional[float] = None,
                 on_wedge: Optional[Callable] = None):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s} "
                             "(check RAY_TPU_INFER_WATCHDOG)")
        self.engine = engine
        self.timeout_s = float(timeout_s)
        self.poll_s = poll_s if poll_s is not None else \
            min(self.timeout_s / 4, 0.5)
        self.on_wedge = on_wedge
        self.wedges = 0
        # wedge-signal export: when the last episode fired (surfaced
        # through the fleet replica's stats beside the counter, so an
        # operator can tell a fresh wedge from an old one) — None
        # until the first episode
        self.last_wedge_ts: Optional[float] = None
        self._fired_at_tick: Optional[int] = None
        # idle->busy tracking: after an idle stretch the engine's
        # last_tick_ts is stale by construction (nothing steps an
        # empty engine), so the stall clock restarts when work arrives
        self._idle = True
        self._busy_since = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ check
    def check(self, now: Optional[float] = None) -> bool:
        """One liveness probe (the thread calls this; tests can too).
        Returns True when a wedge fired on this probe."""
        now = time.monotonic() if now is None else now
        eng = self.engine
        if not eng.has_work():
            self._fired_at_tick = None      # idle: nothing to stall
            self._idle = True
            return False
        if self._idle:
            # idle -> busy transition: the last tick stamp predates
            # this work, so judging it against timeout_s would fire a
            # false wedge on the first request after any idle stretch
            # (worst on a cold engine paying its first compile)
            self._idle = False
            self._busy_since = now
            return False
        ticks = eng.ticks
        if now - max(eng.last_tick_ts, self._busy_since) \
                <= self.timeout_s:
            if self._fired_at_tick is not None \
                    and ticks != self._fired_at_tick:
                self._fired_at_tick = None  # progress resumed: re-arm
            return False
        if self._fired_at_tick == ticks:
            return False                    # this episode already fired
        self._fired_at_tick = ticks
        self.wedges += 1
        self.last_wedge_ts = now
        if self.on_wedge is not None:
            try:
                self.on_wedge(eng)
            except Exception as e:  # noqa: BLE001 — never kill the dog
                print(f"EngineWatchdog on_wedge callback failed: "
                      f"{e!r}", file=sys.stderr)
        else:
            print(f"EngineWatchdog: engine wedged — work pending and "
                  f"no step completed for > {self.timeout_s:.1f}s "
                  f"(ticks={ticks}, waiting="
                  f"{len(eng.scheduler.waiting)}, active="
                  f"{len(eng.scheduler.active)})", file=sys.stderr)
        return True

    # -------------------------------------------------------- lifecycle
    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — watchdog must survive
                pass

    def start(self) -> "EngineWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="engine-watchdog")
            self._thread.start()
        return self

    def stop(self) -> int:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        return self.wedges

    def __enter__(self) -> "EngineWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
