"""Training straggler supervisor: detect the step that is slow, not
dead.

A synchronous train step is gated by its slowest participant — the TPU
concurrency study (arXiv:2011.03641) makes step time the max over
hosts, so one straggling host taxes every step of the run, forever,
without tripping any of the binary fault machinery (r15 deadlines, r16
watchdogs, r18 ``mesh.loss``).  Podracer-style decoupling
(arXiv:2104.06272) tolerates stragglers on the actor side because
slowness is *detected* and routed around; this module gives the
synchronous train loop the detection half, and
:func:`~ray_tpu.resilience.elastic.run_elastic_train_loop` converts a
sustained straggle into the degraded-mesh shrink the r18 machinery
already knows how to survive — snapshot, rebuild without the
straggler's capacity, keep the global batch via scaled gradient
accumulation — instead of stalling the run at the straggler's pace.

Detection is deliberately conservative (the fleet-median/dwell
vocabulary the r19 serve layer uses):

- the **baseline** is a rolling median of recent *accepted* step wall
  times — robust to the one-off outlier, and slow samples never enter
  it (a sustained straggle must not drag the baseline up until the
  straggle looks normal);
- a step is **slow** when its wall exceeds
  ``RAY_TPU_STRAGGLER_FACTOR`` x the baseline;
- only ``RAY_TPU_STRAGGLER_DWELL`` *consecutive* slow steps fire an
  event — a cold compile, a GC pause or one preempted host tick is a
  blip, not a straggle (and the first steps of a run cannot fire at
  all: the baseline needs ``min_samples`` accepted steps first).

The ``mesh.step`` chaos site (``util/chaos.py``,
``mesh.step@N..M:delay=S``) injects exactly this failure mode
deterministically: the elastic loop's step wall stretches by ``S`` for
the window, and the supervisor must convert it into a shrink.
"""

from __future__ import annotations

import collections
import statistics
from typing import Deque, Dict, List

from ray_tpu.resilience.config import resilience_config


class StragglerSupervisor:
    """Per-step wall-time watcher; :meth:`observe` returns True when a
    sustained straggle should be handled as a degraded-mesh event.

    ``factor``/``dwell``/``window`` default from
    ``RAY_TPU_STRAGGLER_{FACTOR,DWELL,WINDOW}``; ``factor=0`` disables
    (every observe returns False).  Call :meth:`reset` after any
    topology change — step walls legitimately shift with the mesh size
    and accumulation factor, and a stale baseline would misread the
    new normal as a straggle.

    Baselines are kept **per tier** (the ``tier`` kwarg on
    :meth:`observe`/:meth:`baseline_s`): a DCN-crossing step on a
    multi-pod mesh is legitimately slower than an ICI-only one, so
    flagging it against an ICI baseline would convert every cross-pod
    step into a phantom straggle.  The elastic loop passes
    ``tier="dcn"`` when the live mesh has a ``dcn`` axis > 1 and
    ``tier="ici"`` otherwise; callers that never mix tiers can ignore
    the kwarg (everything lands in one ``"default"`` bucket).  Slow
    streaks are per-tier too — alternating tiers must not interleave
    into one phantom streak.
    """

    def __init__(self, *, factor: float = None, dwell: int = None,
                 window: int = None, min_samples: int = 3):
        rcfg = resilience_config()
        self.factor = rcfg.straggler_factor if factor is None \
            else float(factor)
        self.dwell = rcfg.straggler_dwell if dwell is None \
            else int(dwell)
        if self.dwell < 1:
            raise ValueError(f"straggler dwell must be >= 1, got "
                             f"{self.dwell} (RAY_TPU_STRAGGLER_DWELL)")
        window = rcfg.straggler_window if window is None else int(window)
        if window < min_samples:
            raise ValueError(
                f"straggler window ({window}) must hold at least "
                f"min_samples ({min_samples}) steps")
        self.min_samples = int(min_samples)
        self._window = int(window)
        self._walls: Dict[str, Deque[float]] = {}
        self._streaks: Dict[str, int] = {}
        self.events = 0
        self.slow_steps = 0
        self.event_log: List[dict] = []

    @property
    def enabled(self) -> bool:
        return self.factor > 0

    def _tier_walls(self, tier: str) -> Deque[float]:
        if tier not in self._walls:
            self._walls[tier] = collections.deque(maxlen=self._window)
        return self._walls[tier]

    def baseline_s(self, tier: str = "default") -> float:
        """The tier's rolling-median step wall (0.0 until enough
        samples)."""
        walls = self._walls.get(tier)
        if walls is None or len(walls) < self.min_samples:
            return 0.0
        return statistics.median(walls)

    def observe(self, wall_s: float, tier: str = "default") -> bool:
        """Feed one step's wall seconds; True when this step completes
        a sustained straggle (``dwell`` consecutive slow steps against
        the SAME tier's baseline) — the caller should shrink the mesh
        and :meth:`reset`."""
        if not self.enabled:
            return False
        wall_s = float(wall_s)
        walls = self._tier_walls(tier)
        base = self.baseline_s(tier)
        if base <= 0.0:
            # baseline still forming: accept unconditionally — the
            # cold-compile step lands here as one median-robust
            # outlier, never as a straggle verdict
            walls.append(wall_s)
            return False
        if wall_s <= self.factor * base:
            walls.append(wall_s)
            self._streaks[tier] = 0
            return False
        # slow: count the streak, keep the sample OUT of the baseline
        self.slow_steps += 1
        streak = self._streaks.get(tier, 0) + 1
        self._streaks[tier] = streak
        if streak < self.dwell:
            return False
        self.events += 1
        self.event_log.append({"wall_s": round(wall_s, 6),
                               "baseline_s": round(base, 6),
                               "streak": streak,
                               "tier": tier})
        self._streaks[tier] = 0
        return True

    def reset(self) -> None:
        """Forget every tier's baseline and streak (topology changed:
        the new mesh has a new normal)."""
        self._walls.clear()
        self._streaks.clear()
