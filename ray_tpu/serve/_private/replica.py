"""Replica actor wrapping the user's deployment callable.

Parity: reference ``serve/_private/replica.py`` (compressed): executes
requests against the user class, tracks in-flight count for
power-of-two-choices routing, supports async and sync callables.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, Tuple

import ray_tpu


def get_multiplexed_model_id() -> str:
    from ray_tpu.serve._private.mux_context import get_model_id
    return get_model_id()


@ray_tpu.remote
class ServeReplica:
    def __init__(self, app_name: str, deployment_name: str,
                 cls_blob: bytes, init_args: Tuple, init_kwargs: Dict,
                 user_config=None):
        import cloudpickle
        cls = cloudpickle.loads(cls_blob)
        if inspect.isfunction(cls):
            self.instance = cls
        else:
            self.instance = cls(*init_args, **init_kwargs)
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._ongoing = 0
        if user_config is not None and hasattr(self.instance,
                                               "reconfigure"):
            # applied synchronously: the replica must not serve requests
            # (or report ready) with the config unapplied, and a failing
            # reconfigure must fail the replica like the reference does.
            # Actor __init__ runs before the actor's event loop starts,
            # so asyncio.run is safe for async reconfigure.
            out = self.instance.reconfigure(user_config)
            if inspect.iscoroutine(out):
                import asyncio
                asyncio.run(out)

    def ping(self):
        return "pong"

    def num_ongoing(self) -> int:
        return self._ongoing

    async def handle_request(self, method_name: str, args, kwargs,
                             mux_model_id: str = ""):
        from ray_tpu.serve._private import mux_context
        self._ongoing += 1
        token = mux_context.set_model_id(mux_model_id)
        try:
            if callable(self.instance) and method_name == "__call__":
                fn = self.instance
            else:
                fn = getattr(self.instance, method_name)
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            mux_context.reset(token)
            self._ongoing -= 1

    async def handle_request_streaming(self, method_name: str, args,
                                       kwargs, mux_model_id: str = ""):
        """Streaming variant: the user callable's (a)sync generator is
        re-yielded item by item; called with ``num_returns="streaming"``
        each item becomes an object-ref slot as produced (parity:
        reference replica.py streaming via ObjectRefGenerator)."""
        from ray_tpu.serve._private import mux_context
        self._ongoing += 1
        token = mux_context.set_model_id(mux_model_id)
        try:
            if callable(self.instance) and method_name == "__call__":
                fn = self.instance
            else:
                fn = getattr(self.instance, method_name)
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            if inspect.isasyncgen(result):
                async for item in result:
                    yield item
            elif inspect.isgenerator(result) or (
                    hasattr(result, "__iter__")
                    and not isinstance(result,
                                       (list, tuple, dict, str, bytes))):
                for item in result:
                    yield item
            else:
                yield result
        finally:
            mux_context.reset(token)
            self._ongoing -= 1

    async def reconfigure(self, user_config):
        if hasattr(self.instance, "reconfigure"):
            out = self.instance.reconfigure(user_config)
            if inspect.iscoroutine(out):
                await out
        return True
