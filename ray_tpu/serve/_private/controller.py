"""ServeController — declarative app reconciliation.

Parity: reference ``serve/_private/controller.py`` + ``deployment_state.py``
(compressed): the controller is a detached actor holding the desired state
of every application; deploying reconciles replica actors to the target
count; handles query it for routing tables (pull-based instead of the
reference's long-poll push, same information flow).
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "__serve_controller__"


@ray_tpu.remote
class ServeController:
    def __init__(self):
        # app -> deployment -> {"config":..., "replicas": [handles],
        #                       "version": int}
        self.apps: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self.ingress: Dict[str, str] = {}  # app -> ingress deployment
        self.proxy = None

    async def deploy_application(self, app_name: str,
                                 deployments: List[Dict[str, Any]],
                                 ingress_name: str):
        """deployments: [{name, cls_blob, init_args, init_kwargs,
        num_replicas, actor_options, max_ongoing}]"""
        import cloudpickle
        app = self.apps.setdefault(app_name, {})
        desired = {d["name"] for d in deployments}
        # tear down removed deployments
        for name in list(app):
            if name not in desired:
                for replica in app[name]["replicas"]:
                    ray_tpu.kill(replica)
                del app[name]
        from ray_tpu.serve._private.replica import ServeReplica
        for d in deployments:
            entry = app.get(d["name"])
            version = (entry["version"] + 1) if entry else 1
            if entry:  # in-place update: replace replicas
                for replica in entry["replicas"]:
                    ray_tpu.kill(replica)
            replicas = []
            for i in range(d["num_replicas"]):
                opts = dict(d.get("actor_options") or {})
                opts.setdefault("num_cpus", 0)
                opts["max_concurrency"] = max(
                    d.get("max_ongoing", 8), 1)
                replicas.append(ServeReplica.options(**opts).remote(
                    app_name, d["name"], d["cls_blob"],
                    d.get("init_args") or (),
                    d.get("init_kwargs") or {}))
            app[d["name"]] = {"config": {k: v for k, v in d.items()
                                         if k != "cls_blob"},
                              "replicas": replicas,
                              "version": version}
        self.ingress[app_name] = ingress_name
        # wait for all replicas to be live
        pings = []
        for name in desired:
            for replica in app[name]["replicas"]:
                pings.append(replica.ping.remote())
        for ref in pings:
            await ref
        return True

    def get_routing(self, app_name: str,
                    deployment: Optional[str] = None):
        app = self.apps.get(app_name)
        if app is None:
            return None
        name = deployment or self.ingress.get(app_name)
        entry = app.get(name)
        if entry is None:
            return None
        return {"deployment": name, "replicas": entry["replicas"],
                "version": entry["version"],
                "max_ongoing": entry["config"].get("max_ongoing", 8)}

    def list_applications(self):
        return {app: {"deployments": {
            name: {"num_replicas": len(e["replicas"]),
                   "version": e["version"]}
            for name, e in deps.items()},
            "ingress": self.ingress.get(app)}
            for app, deps in self.apps.items()}

    def delete_application(self, app_name: str):
        app = self.apps.pop(app_name, None)
        self.ingress.pop(app_name, None)
        if app:
            for entry in app.values():
                for replica in entry["replicas"]:
                    ray_tpu.kill(replica)
        return True

    def set_proxy(self, proxy):
        self.proxy = proxy

    def get_proxy(self):
        return self.proxy

    def ping(self):
        return "pong"
