"""ServeController — declarative app reconciliation + autoscaling.

Parity: reference ``serve/_private/controller.py`` + ``deployment_state.py``
+ ``autoscaling_policy.py`` (compressed): a detached actor holds the
desired state of every application and reconciles replica actors to it.
An async autoscale loop sizes each deployment from measured queue depth
(``ceil(total_ongoing / target_ongoing_requests)`` clamped to
[min, max], with upscale/downscale sustain delays).  Routing-table
changes are *pushed* to handles through the control-plane pubsub
(reference ``_private/long_poll.py:64,173``) instead of polled.

Redeploys are minimally disruptive: if only ``user_config`` changed, the
live replicas are reconfigured in place (no restart); replica-count
changes add/remove the delta.
"""

from __future__ import annotations

import asyncio
import math
import time
from typing import Any, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "__serve_controller__"

AUTOSCALE_DEFAULTS = {
    "min_replicas": 1,
    # max_replicas defaults to num_replicas at deploy time
    "target_ongoing_requests": 2.0,
    "upscale_delay_s": 0.5,
    "downscale_delay_s": 2.0,
    "metrics_interval_s": 0.25,
}


def routing_channel(app_name: str, deployment: str) -> str:
    return f"serve_routing:{app_name}:{deployment}"


def _cp():
    from ray_tpu._private.worker import global_worker
    return global_worker().cp


@ray_tpu.remote
class ServeController:
    def __init__(self):
        # app -> deployment -> {"config":..., "replicas": [handles],
        #   "version": int, "blob": bytes, "autoscale": dict|None,
        #   "desired_since": (direction, t0)}
        self.apps: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self.ingress: Dict[str, str] = {}  # app -> ingress deployment
        self.proxy = None
        # started from the first async call: __init__ runs before the
        # actor's event loop exists, so a task created here would never
        # be scheduled
        self._autoscaler: Optional[asyncio.Task] = None
        # strong refs: the loop holds tasks weakly, and a GC'd drain
        # task would leak its unrouted victims forever
        self._drain_tasks: set = set()

    def _ensure_autoscaler(self) -> None:
        if self._autoscaler is None or self._autoscaler.done():
            self._autoscaler = asyncio.ensure_future(
                self._autoscale_loop())

    # ------------------------------------------------------- deploy ----
    def _spawn_replica(self, app_name: str, d: Dict[str, Any]):
        from ray_tpu.serve._private.replica import ServeReplica
        opts = dict(d.get("actor_options") or {})
        opts.setdefault("num_cpus", 0)
        opts["max_concurrency"] = max(d.get("max_ongoing", 8), 1)
        return ServeReplica.options(**opts).remote(
            app_name, d["name"], d["cls_blob"],
            d.get("init_args") or (), d.get("init_kwargs") or {},
            d.get("user_config"))

    def _publish(self, app_name: str, name: str, version: int) -> None:
        try:
            _cp().publish(routing_channel(app_name, name),
                          {"version": version})
        except Exception:  # noqa: BLE001 — pubsub is best-effort
            pass

    @staticmethod
    def _same_code(entry: Dict[str, Any], d: Dict[str, Any]) -> bool:
        return (entry["blob"] == d["cls_blob"]
                and entry["config"].get("init_args") == (
                    d.get("init_args") or ())
                and entry["config"].get("init_kwargs") == (
                    d.get("init_kwargs") or {})
                and entry["config"].get("actor_options") ==
                d.get("actor_options"))

    async def deploy_application(self, app_name: str,
                                 deployments: List[Dict[str, Any]],
                                 ingress_name: str):
        """deployments: [{name, cls_blob, init_args, init_kwargs,
        num_replicas, actor_options, max_ongoing, user_config,
        autoscaling_config}]"""
        self._ensure_autoscaler()
        app = self.apps.setdefault(app_name, {})
        desired = {d["name"] for d in deployments}
        for name in list(app):  # tear down removed deployments
            if name not in desired:
                for replica in app[name]["replicas"]:
                    ray_tpu.kill(replica)
                del app[name]
        pings = []
        for d in deployments:
            autoscale = None
            if d.get("autoscaling_config") is not None:
                autoscale = dict(AUTOSCALE_DEFAULTS)
                autoscale.update(d["autoscaling_config"])
                # min_replicas=0 is supported: a handle that finds the
                # routing table empty calls request_upscale and waits
                # for the push carrying the first replica
                autoscale.setdefault(
                    "max_replicas",
                    max(d["num_replicas"], autoscale["min_replicas"], 1))
            target_n = (autoscale["min_replicas"] if autoscale
                        else d["num_replicas"])
            entry = app.get(d["name"])
            if entry and self._same_code(entry, d):
                # lightweight redeploy: reconfigure in place, adjust count
                version = entry["version"] + 1
                if autoscale:
                    # keep the autoscaler-earned count, clamped to the
                    # (possibly new) bounds — don't snap back to min
                    target_n = max(autoscale["min_replicas"],
                                   min(autoscale["max_replicas"],
                                       len(entry["replicas"])))
                if len(entry["replicas"]) > target_n:
                    victims = entry["replicas"][target_n:]
                    del entry["replicas"][target_n:]
                    self._schedule_drain(victims)
                while len(entry["replicas"]) < target_n:
                    entry["replicas"].append(
                        self._spawn_replica(app_name, d))
                # reconfigure only the survivors (after any shrink)
                if entry["config"].get("user_config") != \
                        d.get("user_config"):
                    for replica in entry["replicas"]:
                        pings.append(replica.reconfigure.remote(
                            d.get("user_config")))
                entry["config"] = {k: v for k, v in d.items()
                                   if k != "cls_blob"}
                entry["version"] = version
                entry["autoscale"] = autoscale
                entry["desired_since"] = None
            else:
                if entry:  # code changed: replace replicas
                    for replica in entry["replicas"]:
                        ray_tpu.kill(replica)
                replicas = [self._spawn_replica(app_name, d)
                            for _ in range(target_n)]
                app[d["name"]] = {
                    "config": {k: v for k, v in d.items()
                               if k != "cls_blob"},
                    "blob": d["cls_blob"],
                    "replicas": replicas,
                    "version": (entry["version"] + 1) if entry else 1,
                    "autoscale": autoscale,
                    "desired_since": None,
                }
            self._publish(app_name, d["name"],
                          app[d["name"]]["version"])
        self.ingress[app_name] = ingress_name
        for name in desired:  # wait for live replicas + reconfigures
            for replica in app[name]["replicas"]:
                pings.append(replica.ping.remote())
        for ref in pings:
            await ref
        return True

    # --------------------------------------------------- autoscaling ----
    async def _autoscale_loop(self):
        """Queue-depth-driven scaling (reference autoscaling_policy.py:1:
        desired = ceil(total_ongoing / target), sustained over the
        up/downscale delay before acting)."""
        while True:
            try:
                await asyncio.sleep(0.25)
                now = time.monotonic()
                for app_name, deps in list(self.apps.items()):
                    for name, entry in list(deps.items()):
                        cfg = entry.get("autoscale")
                        if not cfg:
                            continue
                        last = entry.get("last_probe", 0.0)
                        if now - last < cfg["metrics_interval_s"]:
                            continue
                        entry["last_probe"] = now
                        await self._autoscale_one(app_name, name,
                                                  entry, cfg)
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — keep the loop alive
                pass

    async def request_upscale(self, app_name: str, name: str) -> bool:
        """Scale-from-zero wakeup: a handle found no replicas to route
        to.  Spawn one immediately (the autoscaler grows it further if
        load sustains) and push the new routing table."""
        entry = self.apps.get(app_name, {}).get(name)
        if entry is None:
            return False
        if entry["replicas"]:
            return True
        d = dict(entry["config"])
        d["cls_blob"] = entry["blob"]
        replica = self._spawn_replica(app_name, d)
        entry["replicas"].append(replica)
        entry["version"] += 1
        entry["desired_since"] = None
        self._publish(app_name, name, entry["version"])
        try:
            await replica.ping.remote()
        except Exception:  # noqa: BLE001 — handle retries routing anyway
            pass
        return True

    async def _autoscale_one(self, app_name: str, name: str,
                             entry: Dict[str, Any], cfg: Dict[str, Any]):
        replicas = entry["replicas"]
        if not replicas:
            return
        # snapshot: a same-code redeploy can mutate the list in place
        # while we await probes (counts must pair with these replicas)
        replicas = list(replicas)

        async def probe(r):
            try:
                return await r.num_ongoing.remote()
            except Exception:  # noqa: BLE001 — dead replica counts 0
                return 0

        counts = list(await asyncio.gather(*[probe(r) for r in replicas]))
        # a concurrent redeploy may have replaced this entry while we
        # were suspended on the probes — mutating the old dict would
        # spawn replicas into an orphaned list
        if self.apps.get(app_name, {}).get(name) is not entry:
            return
        total = sum(counts)
        desired = math.ceil(total / max(cfg["target_ongoing_requests"],
                                        1e-9))
        desired = max(cfg["min_replicas"],
                      min(cfg["max_replicas"], desired))
        current = len(replicas)
        if desired == current:
            entry["desired_since"] = None
            return
        direction = "up" if desired > current else "down"
        mark = entry.get("desired_since")
        now = time.monotonic()
        if mark is None or mark[0] != direction:
            entry["desired_since"] = (direction, now)
            return
        delay = (cfg["upscale_delay_s"] if direction == "up"
                 else cfg["downscale_delay_s"])
        if now - mark[1] < delay:
            return
        entry["desired_since"] = None
        d = dict(entry["config"])
        d["cls_blob"] = entry["blob"]
        if direction == "up":
            for _ in range(desired - current):
                entry["replicas"].append(self._spawn_replica(app_name, d))
            entry["version"] += 1
            self._publish(app_name, name, entry["version"])
        else:
            # drain-then-kill: remove the least-loaded replicas from the
            # routing table first (version bump pushes the new table to
            # handles), wait for their in-flight requests to finish,
            # then kill (reference: replica graceful shutdown /
            # drain_replicas)
            order = sorted(range(current), key=lambda i: counts[i])
            victims = [replicas[i] for i in order[:current - desired]]
            for v in victims:
                if v in entry["replicas"]:
                    entry["replicas"].remove(v)
            entry["version"] += 1
            self._publish(app_name, name, entry["version"])
            self._schedule_drain(victims)

    def _schedule_drain(self, victims) -> None:
        task = asyncio.ensure_future(self._drain_and_kill(victims))
        self._drain_tasks.add(task)
        task.add_done_callback(self._drain_tasks.discard)

    async def _drain_and_kill(self, victims, timeout_s: float = 30.0,
                              grace_s: float = 1.0):
        # grace: handles learn about the routing change via the pubsub
        # push; requests dispatched from a stale table in that window
        # are invisible to num_ongoing until they start executing
        await asyncio.sleep(grace_s)
        deadline = time.monotonic() + timeout_s
        pending = list(victims)
        while pending and time.monotonic() < deadline:
            still = []
            for r in pending:
                busy = False
                try:
                    busy = await r.num_ongoing.remote() > 0
                except Exception:  # noqa: BLE001 — probe failed: kill
                    pass           # anyway (kill tolerates dead actors)
                if busy:
                    still.append(r)
                    continue
                try:
                    ray_tpu.kill(r)
                except Exception:  # noqa: BLE001
                    pass
            pending = still
            if pending:
                await asyncio.sleep(0.2)
        for r in pending:  # drain timeout: cut them loose
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------- routing ----
    def get_routing(self, app_name: str,
                    deployment: Optional[str] = None):
        app = self.apps.get(app_name)
        if app is None:
            return None
        name = deployment or self.ingress.get(app_name)
        entry = app.get(name)
        if entry is None:
            return None
        return {"deployment": name, "replicas": list(entry["replicas"]),
                "version": entry["version"],
                "max_ongoing": entry["config"].get("max_ongoing", 8),
                "asgi": entry["config"].get("asgi", False)}

    def list_applications(self):
        return {app: {"deployments": {
            name: {"num_replicas": len(e["replicas"]),
                   "version": e["version"]}
            for name, e in deps.items()},
            "ingress": self.ingress.get(app)}
            for app, deps in self.apps.items()}

    def delete_application(self, app_name: str):
        app = self.apps.pop(app_name, None)
        self.ingress.pop(app_name, None)
        if app:
            for entry in app.values():
                for replica in entry["replicas"]:
                    ray_tpu.kill(replica)
        return True

    def set_proxy(self, proxy):
        self.proxy = proxy

    def get_proxy(self):
        return self.proxy

    def set_grpc_proxy(self, proxy, port: Optional[int] = None):
        self.grpc_proxy = proxy
        self.grpc_port = port

    def get_grpc_proxy(self):
        return getattr(self, "grpc_proxy", None)

    def get_grpc_port(self):
        return getattr(self, "grpc_port", None)

    def ping(self):
        return "pong"
