"""Request-scoped multiplexed-model id.

Lives in its own module (imported inside functions at call time):
cloudpickle ships the replica class by value, and a ContextVar captured
in its globals is unpicklable.
"""

from __future__ import annotations

import contextvars

_mux_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_serve_mux_model_id", default="")


def set_model_id(model_id: str):
    return _mux_model_id.set(model_id)


def reset(token) -> None:
    _mux_model_id.reset(token)


def get_model_id() -> str:
    return _mux_model_id.get()
