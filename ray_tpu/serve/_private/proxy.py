"""HTTP proxy actor (parity: reference ``serve/_private/proxy.py``).

aiohttp server inside an async actor: routes ``/<app>`` (and ``/`` to the
default app) to the app's ingress deployment handle; JSON bodies become
the callable's argument, JSON-able returns become the response.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any

import ray_tpu


@ray_tpu.remote
class HTTPProxy:
    def __init__(self, port: int = 8000):
        # NOTE: __init__ runs before the actor's event loop starts; the
        # server is brought up lazily from the first ready() call.
        self.port = port
        self._runner = None
        self._ready = False
        self._starting = False

    async def _start(self):
        from aiohttp import web

        async def handle(request: "web.Request"):
            from ray_tpu.serve.handle import DeploymentHandle
            path = request.path.strip("/")
            app_name = path.split("/")[0] if path else "default"
            try:
                body: Any = None
                if request.can_read_body:
                    raw = await request.read()
                    if raw:
                        try:
                            body = json.loads(raw)
                        except json.JSONDecodeError:
                            body = raw.decode()
                handle = DeploymentHandle(app_name)
                loop = asyncio.get_running_loop()
                response = await loop.run_in_executor(
                    None, lambda: handle.remote(body).result(60.0))
                if isinstance(response, (dict, list, int, float, bool)) \
                        or response is None:
                    return web.json_response(response)
                return web.Response(text=str(response))
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": str(e)}, status=500)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", self.port)
        await site.start()
        self._ready = True

    async def ready(self):
        if not self._starting:
            self._starting = True
            asyncio.ensure_future(self._start())
        for _ in range(200):
            if self._ready:
                return self.port
            await asyncio.sleep(0.05)
        raise RuntimeError("proxy failed to start")
