"""Ingress proxies (parity: reference ``serve/_private/proxy.py``).

Dual protocol, like the reference's GenericProxy split into HTTPProxy
(``proxy.py:747``) and gRPCProxy (``proxy.py:533``):

- :class:`HTTPProxy` — aiohttp server in an async actor.  Requests ride
  the deployment handle asynchronously (``await ref``), one coroutine
  per request — no thread-per-request.  A request with
  ``?stream=1`` (or header ``X-Serve-Streaming: 1``) hits the
  deployment's streaming path and the response body is chunked: one
  JSON line per yielded item (SSE-flavored ``data:`` framing when the
  client asks for ``text/event-stream``).
- :class:`GRPCProxy` — grpc.aio server exposing a generic byte service
  (``/ray_tpu.serve.GenericService/Predict`` unary and
  ``/.../PredictStreaming`` server-streaming).  The application is
  selected by the ``application`` metadata key (reference uses the same
  key); payloads are JSON if they parse, raw bytes otherwise.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any

import ray_tpu

GRPC_SERVICE = "ray_tpu.serve.GenericService"


def _decode_body(raw: bytes) -> Any:
    if not raw:
        return None
    try:
        return json.loads(raw)
    except (json.JSONDecodeError, UnicodeDecodeError):
        try:
            return raw.decode()
        except UnicodeDecodeError:
            return raw


def _encode_item(item: Any) -> bytes:
    if isinstance(item, bytes):
        return item
    try:
        return json.dumps(item).encode()
    except (TypeError, ValueError):
        return str(item).encode()


@ray_tpu.remote
class HTTPProxy:
    def __init__(self, port: int = 8000, host: str = "127.0.0.1"):
        # NOTE: __init__ runs before the actor's event loop starts; the
        # server is brought up lazily from the first ready() call.
        self.port = port
        self.host = host
        self._runner = None
        self._ready = False
        self._starting = False
        self._handles = {}

    async def _start(self):
        from aiohttp import web

        async def handle(request: "web.Request"):
            path = request.path.strip("/")
            app_name = path.split("/")[0] if path else "default"
            if await self._is_asgi(app_name):
                return await self._asgi_dispatch(app_name, request)
            stream = (request.query.get("stream") == "1"
                      or request.headers.get("X-Serve-Streaming") == "1")
            try:
                body: Any = None
                if request.can_read_body:
                    body = _decode_body(await request.read())
                handle = self._handle(app_name)
                if stream:
                    return await self._stream_response(
                        request, handle, body)
                # async end-to-end: routing fetch + pow-2 probes await
                # on this event loop (handle.remote_async), then the
                # result ref is awaited — no thread per request
                resp_obj = await handle.remote_async(body)
                response = await resp_obj.ref
                if isinstance(response, (dict, list, int, float, bool)) \
                        or response is None:
                    return web.json_response(response)
                return web.Response(text=str(response))
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": str(e)}, status=500)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self._ready = True

    def _handle(self, app_name: str):
        """Cached per-app ingress handles: each handle owns a routing
        cache + long-poll thread, so per-request construction would
        refetch routing from the controller every call."""
        from ray_tpu.serve.handle import DeploymentHandle
        h = self._handles.get(app_name)
        if h is None:
            # bounded LRU: the key is a client-supplied path segment, so
            # unique bogus paths must not grow this without limit
            if len(self._handles) >= 256:
                evict = next(iter(self._handles))
                self._handles.pop(evict, None)
            h = self._handles[app_name] = DeploymentHandle(app_name)
        else:
            # move-to-end for LRU recency
            self._handles[app_name] = self._handles.pop(app_name)
        return h

    async def _is_asgi(self, app_name: str) -> bool:
        """Whether this app's ingress is an ASGI deployment — read per
        request from the handle's routing table, which the long-poll
        invalidates on redeploy (a positive cache here would survive an
        ASGI→plain redeploy and dispatch a method the new replicas
        don't have)."""
        try:
            routing = await self._handle(app_name)._get_routing_async()
        except Exception:  # noqa: BLE001 — unknown app: default path
            return False
        return bool(routing.get("asgi"))

    async def _asgi_dispatch(self, app_name: str, request):
        """Forward the request as one ASGI cycle on an ingress replica
        (reference: ``serve.ingress(fastapi_app)``, serve/api.py:168)."""
        from aiohttp import web
        prefix = f"/{app_name}"
        path = request.path
        if path.startswith(prefix):
            path = path[len(prefix):] or "/"
        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request.method,
            "scheme": request.scheme,
            "path": path,
            "raw_path": path.encode(),
            "root_path": "",
            "query_string": request.query_string.encode(),
            "headers": [(k.lower(), v)
                        for k, v in request.headers.items()],
            "client": (request.remote, 0),
            "server": (self.host, self.port),
        }
        body = await request.read() if request.can_read_body else b""
        handle = self._handle(app_name).options(
            method_name="__serve_asgi__")
        try:
            resp_obj = await handle.remote_async(scope, body)
            result = await resp_obj.ref
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        from multidict import CIMultiDict
        headers = CIMultiDict()
        for k, v in result.get("headers", []):
            # multidict: repeated headers (Set-Cookie!) must survive
            if k.lower() not in ("content-length", "transfer-encoding"):
                headers.add(k, v)
        return web.Response(status=result.get("status", 200),
                            headers=headers,
                            body=result.get("body", b""))

    async def _stream_response(self, request, handle, body):
        from aiohttp import web
        sse = "text/event-stream" in request.headers.get("Accept", "")
        resp = web.StreamResponse(
            headers={"Content-Type": ("text/event-stream" if sse
                                      else "application/x-ndjson")})
        await resp.prepare(request)
        gen = await handle.options(stream=True).remote_async(body)
        async for ref in gen.ref_generator:
            item = await ref
            payload = _encode_item(item)
            if sse:
                await resp.write(b"data: " + payload + b"\n\n")
            else:
                await resp.write(payload + b"\n")
        await resp.write_eof()
        return resp

    async def ready(self):
        if not self._starting:
            self._starting = True
            asyncio.ensure_future(self._start())
        for _ in range(200):
            if self._ready:
                return self.port
            await asyncio.sleep(0.05)
        raise RuntimeError("proxy failed to start")


@ray_tpu.remote
class GRPCProxy:
    """gRPC ingress (parity: reference gRPCProxy, ``proxy.py:533``)."""

    def __init__(self, port: int = 9000, host: str = "127.0.0.1"):
        self.port = port
        self.host = host
        self._server = None
        self._ready = False
        self._starting = False

    async def _start(self):
        import grpc

        def app_from(context) -> str:
            for key, value in (context.invocation_metadata() or ()):
                if key == "application":
                    return value
            return "default"

        async def predict(request: bytes, context):
            from ray_tpu.serve.handle import DeploymentHandle
            handle = DeploymentHandle(app_from(context))
            try:
                loop = asyncio.get_running_loop()
                resp_obj = await loop.run_in_executor(
                    None, lambda: handle.remote(_decode_body(request)))
                result = await resp_obj.ref
            except Exception as e:  # noqa: BLE001
                await context.abort(grpc.StatusCode.INTERNAL, str(e))
                return
            return _encode_item(result)

        async def predict_streaming(request: bytes, context):
            from ray_tpu.serve.handle import DeploymentHandle
            handle = DeploymentHandle(app_from(context))
            try:
                loop = asyncio.get_running_loop()
                gen = await loop.run_in_executor(
                    None, lambda: handle.options(stream=True).remote(
                        _decode_body(request)))
                async for ref in gen.ref_generator:
                    yield _encode_item(await ref)
            except Exception as e:  # noqa: BLE001
                await context.abort(grpc.StatusCode.INTERNAL, str(e))

        ident = lambda b: b  # noqa: E731 — raw-bytes (de)serializer
        handlers = grpc.method_handlers_generic_handler(GRPC_SERVICE, {
            "Predict": grpc.unary_unary_rpc_method_handler(
                predict, request_deserializer=ident,
                response_serializer=ident),
            "PredictStreaming": grpc.unary_stream_rpc_method_handler(
                predict_streaming, request_deserializer=ident,
                response_serializer=ident),
        })
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((handlers,))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        await self._server.start()
        self._ready = True

    async def ready(self):
        if not self._starting:
            self._starting = True
            asyncio.ensure_future(self._start())
        for _ in range(200):
            if self._ready:
                return self.port
            await asyncio.sleep(0.05)
        raise RuntimeError("grpc proxy failed to start")
