"""``ray_tpu.serve`` — model serving (parity: ``ray.serve``).

``@serve.deployment`` → ``.bind(...)`` → ``serve.run(app)`` → handle or
HTTP.  Controller actor reconciles replica actors; handles route with
power-of-two-choices; an aiohttp proxy serves HTTP.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu.serve._private.controller import (CONTROLLER_NAME,
                                               ServeController)
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    ray_actor_options: Optional[Dict[str, Any]] = None
    max_ongoing_requests: int = 8
    user_config: Optional[Dict[str, Any]] = None
    # {min_replicas, max_replicas, target_ongoing_requests,
    #  upscale_delay_s, downscale_delay_s} — queue-depth autoscaling
    # (parity: serve/_private/autoscaling_policy.py)
    autoscaling_config: Optional[Dict[str, Any]] = None

    def options(self, **kwargs) -> "Deployment":
        import dataclasses
        return dataclasses.replace(self, **kwargs)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


class Application:
    def __init__(self, deployment: Deployment, args: Tuple,
                 kwargs: Dict[str, Any]):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


def deployment(func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1,
               ray_actor_options: Optional[Dict] = None,
               max_ongoing_requests: int = 8,
               user_config: Optional[Dict] = None,
               autoscaling_config: Optional[Dict] = None, **ignored):
    """``@serve.deployment`` decorator (parity: serve/api.py:244)."""
    def wrap(target):
        return Deployment(
            target, name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas,
            ray_actor_options=ray_actor_options,
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            autoscaling_config=autoscaling_config)

    if func_or_class is not None:
        return wrap(func_or_class)
    return wrap


def ingress(asgi_app) -> Callable:
    """``@serve.ingress(app)`` — route HTTP through an ASGI application
    (parity: ``serve/api.py:168`` with FastAPI; here ANY ASGI callable
    works, FastAPI included, so the framework carries no FastAPI pin).

    The decorated deployment's replicas run one ASGI request cycle per
    HTTP request forwarded by the proxy: full path/query/header fidelity,
    the app's own routing, middleware and status codes — instead of the
    proxy's default JSON convention.

    ``asgi_app`` may be the ASGI callable itself or a zero-arg factory
    (use a factory when the app isn't picklable)."""
    def wrap(cls):
        if not isinstance(cls, type):
            raise TypeError("@serve.ingress decorates a deployment class")

        async def __serve_asgi__(self, scope: Dict[str, Any],
                                 body: bytes):
            app = getattr(self, "_serve_asgi_app", None)
            if app is None:
                app = asgi_app
                # zero-arg factory vs ASGI callable (3 params)
                import inspect as _inspect
                try:
                    if len(_inspect.signature(app).parameters) == 0:
                        app = app()
                except (TypeError, ValueError):
                    pass
                self._serve_asgi_app = app
            scope = dict(scope)
            scope["headers"] = [(k.encode() if isinstance(k, str) else k,
                                 v.encode() if isinstance(v, str) else v)
                                for k, v in scope.get("headers", [])]
            sent = {"status": 500, "headers": [], "chunks": []}
            got_body = {"done": False}

            async def receive():
                if got_body["done"]:
                    return {"type": "http.disconnect"}
                got_body["done"] = True
                return {"type": "http.request", "body": body or b"",
                        "more_body": False}

            async def send(message):
                if message["type"] == "http.response.start":
                    sent["status"] = message["status"]
                    sent["headers"] = [
                        (k.decode() if isinstance(k, bytes) else k,
                         v.decode() if isinstance(v, bytes) else v)
                        for k, v in message.get("headers", [])]
                elif message["type"] == "http.response.body":
                    sent["chunks"].append(message.get("body", b""))

            await app(scope, receive, send)
            return {"status": sent["status"], "headers": sent["headers"],
                    "body": b"".join(sent["chunks"])}

        cls.__serve_asgi__ = __serve_asgi__
        cls.__serve_is_asgi__ = True
        return cls

    return wrap


# ------------------------------------------------------------------ run
def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        try:
            return ServeController.options(
                name=CONTROLLER_NAME, lifetime="detached",
                max_concurrency=16).remote()
        except ValueError:
            return ray_tpu.get_actor(CONTROLLER_NAME)


def _collect_deployments(app: Application, app_name: str,
                         out: List[Dict[str, Any]]) -> str:
    """DFS the bind graph; nested Applications become handles."""
    dep = app.deployment

    def resolve(value):
        if isinstance(value, Application):
            child_name = _collect_deployments(value, app_name, out)
            return DeploymentHandle(app_name, child_name)
        return value

    args = tuple(resolve(a) for a in app.args)
    kwargs = {k: resolve(v) for k, v in app.kwargs.items()}
    if not any(d["name"] == dep.name for d in out):
        out.append({
            "name": dep.name,
            "cls_blob": cloudpickle.dumps(dep.func_or_class),
            "init_args": args,
            "init_kwargs": kwargs,
            "num_replicas": dep.num_replicas,
            "actor_options": dep.ray_actor_options,
            "max_ongoing": dep.max_ongoing_requests,
            "user_config": dep.user_config,
            "autoscaling_config": dep.autoscaling_config,
            "asgi": bool(getattr(dep.func_or_class,
                                 "__serve_is_asgi__", False)),
        })
    return dep.name


def run(app: Application, *, name: str = "default",
        route_prefix: str = "/", blocking: bool = False,
        http_port: Optional[int] = None,
        http_host: str = "127.0.0.1",
        grpc_port: Optional[int] = None) -> DeploymentHandle:
    """Deploy ``app``; proxies bind loopback unless ``http_host`` opts
    into a routable interface (e.g. ``"0.0.0.0"``)."""
    controller = _get_or_create_controller()
    deployments: List[Dict[str, Any]] = []
    ingress = _collect_deployments(app, name, deployments)
    ray_tpu.get(controller.deploy_application.remote(
        name, deployments, ingress), timeout=300)
    if http_port is not None:
        start_http_proxy(http_port, http_host)
    if grpc_port is not None:
        start_grpc_proxy(grpc_port, http_host)
    return DeploymentHandle(name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(name)


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name, deployment_name)


def status() -> Dict[str, Any]:
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.list_applications.remote(), timeout=30)


def delete(name: str) -> None:
    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)


def shutdown() -> None:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    apps = ray_tpu.get(controller.list_applications.remote(), timeout=30)
    for app in list(apps):
        ray_tpu.get(controller.delete_application.remote(app),
                    timeout=60)
    proxy = ray_tpu.get(controller.get_proxy.remote(), timeout=10)
    if proxy is not None:
        ray_tpu.kill(proxy)
    grpc_proxy = ray_tpu.get(controller.get_grpc_proxy.remote(),
                             timeout=10)
    if grpc_proxy is not None:
        ray_tpu.kill(grpc_proxy)
    ray_tpu.kill(ray_tpu.get_actor(CONTROLLER_NAME))


# ------------------------------------------------------------- ingress
def start_http_proxy(port: int = 8000, host: str = "127.0.0.1"):
    from ray_tpu.serve._private.proxy import HTTPProxy
    controller = _get_or_create_controller()
    existing = ray_tpu.get(controller.get_proxy.remote(), timeout=10)
    if existing is not None:
        return existing
    proxy = HTTPProxy.options(max_concurrency=64).remote(port, host)
    ray_tpu.get(proxy.ready.remote(), timeout=60)
    ray_tpu.get(controller.set_proxy.remote(proxy), timeout=10)
    return proxy


def start_grpc_proxy(port: int = 9000, host: str = "127.0.0.1"):
    """gRPC ingress on ``/ray_tpu.serve.GenericService/Predict`` (unary)
    and ``PredictStreaming`` (server-streaming); app picked by the
    ``application`` metadata key."""
    from ray_tpu.serve._private.proxy import GRPCProxy
    controller = _get_or_create_controller()
    existing = ray_tpu.get(controller.get_grpc_proxy.remote(), timeout=10)
    if existing is not None:
        return existing
    proxy = GRPCProxy.options(max_concurrency=64).remote(port, host)
    bound = ray_tpu.get(proxy.ready.remote(), timeout=60)
    ray_tpu.get(controller.set_grpc_proxy.remote(proxy, bound),
                timeout=10)
    return proxy


# --------------------------------------------------------- multiplexing
def multiplexed(_func=None, *, max_num_models_per_replica: int = 3):
    """``@serve.multiplexed`` — per-replica LRU of loaded model
    versions (parity: ``serve/api.py`` multiplexed + model
    multiplexing): decorate an async ``load_model(self, model_id)``;
    calls hit the cache, misses load and evict least-recently-used.
    Route requests with ``handle.options(multiplexed_model_id=...)``
    and read the id inside with ``get_multiplexed_model_id()``.
    """
    import collections
    import functools

    def wrap(fn):
        @functools.wraps(fn)
        async def wrapper(self, model_id: str):
            import asyncio
            import inspect as _inspect
            cache = getattr(self, "_mux_models", None)
            if cache is None:
                cache = collections.OrderedDict()
                self._mux_models = cache
                self._mux_pending = {}
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            # dedup concurrent misses: one loader per model id, the
            # rest await its future (double-loading a model can OOM a
            # TPU replica)
            pending = self._mux_pending
            fut = pending.get(model_id)
            if fut is not None:
                return await fut
            fut = asyncio.get_running_loop().create_future()
            pending[model_id] = fut
            try:
                model = fn(self, model_id)
                if _inspect.iscoroutine(model):
                    model = await model
                cache[model_id] = model
                cache.move_to_end(model_id)
                # eviction drops the cache reference only; the object
                # finalizes when the last in-flight user releases it
                # (no explicit __del__: double-finalize hazard)
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
                fut.set_result(model)
                return model
            except BaseException as e:
                fut.set_exception(e)
                raise
            finally:
                pending.pop(model_id, None)

        wrapper._is_multiplexed = True
        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


def get_multiplexed_model_id() -> str:
    """The model id the current request was routed with."""
    from ray_tpu.serve._private.replica import get_multiplexed_model_id
    return get_multiplexed_model_id()


# ------------------------------------------------------------- batching
def batch(_func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """``@serve.batch`` — coalesce concurrent calls into one batch call.

    Parity: ``python/ray/serve/batching.py``.  The wrapped method receives
    a list of inputs and must return a list of outputs.
    """
    import asyncio
    import functools

    def wrap(fn):
        # single-event-loop state: no awaits between mutations, so no lock
        state: Dict[str, Any] = {"queue": [], "timer": None}

        async def flush(owner):
            if state["timer"] is not None:
                state["timer"].cancel()
                state["timer"] = None
            items = state["queue"][:max_batch_size]
            del state["queue"][:max_batch_size]
            if not items:
                return
            inputs = [p for p, _ in items]
            try:
                outs = await (fn(owner, inputs) if owner is not None
                              else fn(inputs))
                for (_, fut), out in zip(items, outs):
                    if not fut.done():
                        fut.set_result(out)
            except Exception as e:  # noqa: BLE001
                for _, fut in items:
                    if not fut.done():
                        fut.set_exception(e)
            if state["queue"]:
                asyncio.ensure_future(flush(owner))

        @functools.wraps(fn)
        async def wrapper(self_or_arg, *args):
            # support bound methods (self) and free functions
            if args:
                owner, payload = self_or_arg, args[0]
            else:
                owner, payload = None, self_or_arg
            loop = asyncio.get_running_loop()
            fut = loop.create_future()
            state["queue"].append((payload, fut))
            if len(state["queue"]) >= max_batch_size:
                asyncio.ensure_future(flush(owner))
            elif state["timer"] is None:
                state["timer"] = loop.call_later(
                    batch_wait_timeout_s,
                    lambda: asyncio.ensure_future(flush(owner)))
            return await fut

        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


__all__ = [
    "deployment", "Deployment", "Application", "run", "get_app_handle",
    "get_deployment_handle", "status", "delete", "shutdown",
    "DeploymentHandle", "DeploymentResponse", "batch",
    "multiplexed", "get_multiplexed_model_id",
    "start_http_proxy",
]
