"""DeploymentHandle — the client API for calling deployments.

Parity: reference ``serve/handle.py`` + the power-of-two-choices replica
scheduler (``replica_scheduler/pow_2_scheduler.py``): pick two random
replicas, probe queue lengths, send to the shorter queue.  The routing
table is pulled from the controller and cached (refreshed on version
bump or failure).
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future-like response (parity: serve.handle.DeploymentResponse)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = 60.0):
        return ray_tpu.get(self._ref, timeout=timeout_s)

    def __await__(self):
        return self._ref.__await__()

    @property
    def ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(self, app_name: str, deployment_name: Optional[str] = None,
                 method_name: str = "__call__",
                 multiplexed_model_id: Optional[str] = None):
        self._app = app_name
        self._deployment = deployment_name
        self._method = method_name
        self._routing: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        self._poller: Optional[threading.Thread] = None
        self._poller_stop = threading.Event()
        # model multiplexing: requests for one model id stick to the
        # replica that already loaded it (reference: model-affinity
        # routing in the pow-2 scheduler)
        self._mux_id: Optional[str] = multiplexed_model_id
        self._mux_affinity: Dict[str, Any] = {}

    def _start_poller(self, deployment: str) -> None:
        """Long-poll the control-plane pubsub for routing pushes
        (autoscale/redeploy version bumps) — parity with the reference's
        LongPollClient (``serve/_private/long_poll.py:173``)."""
        with self._lock:
            if self._poller is not None:
                return
            self._poller = True  # placeholder: claim before starting

        import weakref
        handle_ref = weakref.ref(self)  # don't keep the handle alive
        stop = self._poller_stop
        app = self._app

        def loop():
            from ray_tpu._private.worker import global_worker
            from ray_tpu.serve._private.controller import routing_channel
            channel = routing_channel(app, deployment)
            cursor = 0
            while not stop.is_set():
                try:
                    cursor, msgs = global_worker().cp.poll(
                        channel, cursor, 10.0)
                    handle = handle_ref()
                    if handle is None:
                        return  # handle was GC'd: stop polling
                    if msgs:
                        with handle._lock:
                            handle._routing = None  # refetch on next use
                    del handle
                except Exception:  # noqa: BLE001 — retry next round
                    if stop.wait(1.0):
                        return

        self._poller = threading.Thread(target=loop, daemon=True,
                                        name="serve-handle-poll")
        self._poller.start()

    def __del__(self):
        self._poller_stop.set()

    # handle.method.remote(...) sugar (cached: each sub-handle owns a
    # routing cache + long-poll thread, so recreating per access would
    # churn threads)
    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        sub = DeploymentHandle(self._app, self._deployment, name,
                               self._mux_id)
        sub._mux_affinity = self._mux_affinity
        sub._get_routing = self._get_routing
        self.__dict__[name] = sub
        return sub

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        sub = DeploymentHandle(
            self._app, self._deployment, method_name or self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._mux_id)
        # per-request sub-handles delegate routing state to the parent:
        # they must not each pay a controller RPC + long-poll thread
        sub._mux_affinity = self._mux_affinity
        sub._get_routing = self._get_routing
        return sub

    def _controller(self):
        from ray_tpu.serve._private.controller import CONTROLLER_NAME
        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _get_routing(self, refresh: bool = False) -> Dict[str, Any]:
        with self._lock:
            if self._routing is None or refresh:
                routing = ray_tpu.get(
                    self._controller().get_routing.remote(
                        self._app, self._deployment), timeout=30)
                if routing is None:
                    raise RuntimeError(
                        f"no deployment "
                        f"{self._deployment or '(ingress)'} in app "
                        f"{self._app!r}")
                self._routing = routing
            routing = self._routing
        self._start_poller(routing["deployment"])
        return routing

    def _pick_replica(self):
        routing = self._get_routing()
        replicas = routing["replicas"]
        if len(replicas) == 1:
            return replicas[0]
        # power of two choices on queue length
        a, b = random.sample(replicas, 2)
        try:
            qa, qb = ray_tpu.get([a.num_ongoing.remote(),
                                  b.num_ongoing.remote()], timeout=5)
        except Exception:  # noqa: BLE001 - refresh and fall back
            self._get_routing(refresh=True)
            return random.choice(self._get_routing()["replicas"])
        return a if qa <= qb else b

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        mux = self._mux_id
        if mux:
            routing = self._get_routing()
            replica = self._mux_affinity.get(mux)
            if replica is not None and replica in routing["replicas"]:
                try:  # cheap liveness probe, like the pow-2 path
                    ray_tpu.get(replica.num_ongoing.remote(), timeout=5)
                except Exception:  # noqa: BLE001 — crashed: re-pin
                    self._get_routing(refresh=True)
                    replica = None
            else:
                replica = None
            if replica is None:
                replica = self._pick_replica()
                self._mux_affinity[mux] = replica
            ref = replica.handle_request.remote(self._method, args,
                                                kwargs, mux)
            return DeploymentResponse(ref)
        replica = self._pick_replica()
        ref = replica.handle_request.remote(self._method, args, kwargs)
        return DeploymentResponse(ref)

    def __reduce__(self):
        return (DeploymentHandle, (self._app, self._deployment,
                                   self._method, self._mux_id))

    # identity is the target, not the instance: the controller compares
    # init_args across redeploys to decide in-place reconfigure vs
    # restart, and composed apps carry handles in init_args
    def __eq__(self, other):
        return (isinstance(other, DeploymentHandle)
                and (self._app, self._deployment, self._method)
                == (other._app, other._deployment, other._method))

    def __hash__(self):
        return hash((self._app, self._deployment, self._method))
