"""DeploymentHandle — the client API for calling deployments.

Parity: reference ``serve/handle.py`` + the power-of-two-choices replica
scheduler (``replica_scheduler/pow_2_scheduler.py``): pick two random
replicas, probe queue lengths, send to the shorter queue.  The routing
table is pulled from the controller and cached (refreshed on version
bump or failure).
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future-like response (parity: serve.handle.DeploymentResponse)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = 60.0):
        return ray_tpu.get(self._ref, timeout=timeout_s)

    def __await__(self):
        return self._ref.__await__()

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Streaming response: iterate resolved items as the replica yields
    them (parity: serve.handle.DeploymentResponseGenerator over
    ObjectRefGenerator)."""

    def __init__(self, ref_gen):
        self._ref_gen = ref_gen

    def __iter__(self):
        for ref in self._ref_gen:
            yield ray_tpu.get(ref)

    @property
    def ref_generator(self):
        return self._ref_gen


class DeploymentHandle:
    def __init__(self, app_name: str, deployment_name: Optional[str] = None,
                 method_name: str = "__call__",
                 multiplexed_model_id: Optional[str] = None,
                 stream: bool = False):
        self._app = app_name
        self._deployment = deployment_name
        self._method = method_name
        self._stream = stream
        self._routing: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        self._poller: Optional[threading.Thread] = None
        self._poller_stop = threading.Event()
        # model multiplexing: requests for one model id stick to the
        # replica that already loaded it (reference: model-affinity
        # routing in the pow-2 scheduler)
        self._mux_id: Optional[str] = multiplexed_model_id
        self._mux_affinity: Dict[str, Any] = {}
        # liveness probes of pinned replicas are TTL-cached: probing on
        # every dispatch added an RPC round trip per request
        self._mux_probe_ok: Dict[Any, float] = {}

    def _start_poller(self, deployment: str) -> None:
        """Long-poll the control-plane pubsub for routing pushes
        (autoscale/redeploy version bumps) — parity with the reference's
        LongPollClient (``serve/_private/long_poll.py:173``)."""
        with self._lock:
            if self._poller is not None:
                return
            self._poller = True  # placeholder: claim before starting

        import weakref
        handle_ref = weakref.ref(self)  # don't keep the handle alive
        stop = self._poller_stop
        app = self._app

        def loop():
            from ray_tpu._private.worker import global_worker
            from ray_tpu.serve._private.controller import routing_channel
            channel = routing_channel(app, deployment)
            cursor = 0
            while not stop.is_set():
                try:
                    cursor, msgs = global_worker().cp.poll(
                        channel, cursor, 10.0)
                    handle = handle_ref()
                    if handle is None:
                        return  # handle was GC'd: stop polling
                    if msgs:
                        with handle._lock:
                            handle._routing = None  # refetch on next use
                    del handle
                except Exception:  # noqa: BLE001 — retry next round
                    if stop.wait(1.0):
                        return

        self._poller = threading.Thread(target=loop, daemon=True,
                                        name="serve-handle-poll")
        self._poller.start()

    def __del__(self):
        self._poller_stop.set()

    # handle.method.remote(...) sugar (cached: each sub-handle owns a
    # routing cache + long-poll thread, so recreating per access would
    # churn threads)
    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        sub = DeploymentHandle(self._app, self._deployment, name,
                               self._mux_id, stream=self._stream)
        sub._mux_affinity = self._mux_affinity
        sub._mux_probe_ok = self._mux_probe_ok
        sub._get_routing = self._get_routing
        sub._get_routing_async = self._get_routing_async
        self.__dict__[name] = sub
        return sub

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        sub = DeploymentHandle(
            self._app, self._deployment, method_name or self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._mux_id,
            stream=self._stream if stream is None else stream)
        # per-request sub-handles delegate routing state to the parent:
        # they must not each pay a controller RPC + long-poll thread
        # (or lose the probe TTL cache that skips per-dispatch probes)
        sub._mux_affinity = self._mux_affinity
        sub._mux_probe_ok = self._mux_probe_ok
        sub._get_routing = self._get_routing
        sub._get_routing_async = self._get_routing_async
        return sub

    def _controller(self):
        ctrl = self.__dict__.get("_controller_handle")
        if ctrl is None:
            from ray_tpu.serve._private.controller import CONTROLLER_NAME
            ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
            self.__dict__["_controller_handle"] = ctrl
        return ctrl

    def _get_routing(self, refresh: bool = False) -> Dict[str, Any]:
        with self._lock:
            if self._routing is None or refresh:
                routing = ray_tpu.get(
                    self._controller().get_routing.remote(
                        self._app, self._deployment), timeout=30)
                if routing is None:
                    raise RuntimeError(
                        f"no deployment "
                        f"{self._deployment or '(ingress)'} in app "
                        f"{self._app!r}")
                self._routing = routing
            routing = self._routing
        self._start_poller(routing["deployment"])
        return routing

    def _wait_for_replicas(self, timeout_s: float = 30.0):
        """Scale-from-zero: ask the controller for capacity, then wait
        for the routing push to carry a live replica (reference:
        handle-side autoscaling metrics let min_replicas=0 deployments
        wake on first request)."""
        import time as _time
        routing = self._get_routing()
        deadline = _time.monotonic() + timeout_s
        kicked = False
        while not routing["replicas"]:
            if not kicked:
                try:
                    ray_tpu.get(self._controller().request_upscale.remote(
                        self._app, routing["deployment"]), timeout=30)
                except Exception:  # noqa: BLE001 — retried below
                    pass
                kicked = True
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"deployment {routing['deployment']!r} has no "
                    f"replicas after {timeout_s}s")
            _time.sleep(0.1)
            routing = self._get_routing(refresh=True)
        return routing

    def _pick_replica(self):
        routing = self._get_routing()
        if not routing["replicas"]:
            routing = self._wait_for_replicas()
        replicas = routing["replicas"]
        if len(replicas) == 1:
            return replicas[0]
        # power of two choices on queue length
        a, b = random.sample(replicas, 2)
        try:
            qa, qb = ray_tpu.get([a.num_ongoing.remote(),
                                  b.num_ongoing.remote()], timeout=5)
        except Exception:  # noqa: BLE001 - refresh and fall back
            routing = self._get_routing(refresh=True)
            if not routing["replicas"]:
                # scaled to zero while we probed: wake it back up
                routing = self._wait_for_replicas()
            return random.choice(routing["replicas"])
        return a if qa <= qb else b

    _MUX_PROBE_TTL_S = 5.0

    def _dispatch(self, replica, args, kwargs, mux: str = ""):
        if self._stream:
            method = replica.handle_request_streaming.options(
                num_returns="streaming")
            return DeploymentResponseGenerator(
                method.remote(self._method, args, kwargs, mux))
        ref = replica.handle_request.remote(self._method, args, kwargs,
                                            mux)
        return DeploymentResponse(ref)

    # ------------------------------------------------------------------
    # Async dispatch (proxy hot path).  Same routing logic as the sync
    # path, but every wait point — controller fetch, replica probes,
    # scale-from-zero backoff — is awaited on the caller's event loop
    # instead of burning an executor thread per request (reference:
    # the proxy is async end-to-end, ``serve/_private/proxy.py:423``).
    # ------------------------------------------------------------------
    async def _get_routing_async(self, refresh: bool = False):
        with self._lock:
            routing = None if refresh else self._routing
        if routing is None:
            ref = self._controller().get_routing.remote(
                self._app, self._deployment)
            import asyncio
            routing = await asyncio.wait_for(ref, timeout=30)
            if routing is None:
                raise RuntimeError(
                    f"no deployment {self._deployment or '(ingress)'} "
                    f"in app {self._app!r}")
            with self._lock:
                self._routing = routing
        self._start_poller(routing["deployment"])
        return routing

    async def _wait_for_replicas_async(self, timeout_s: float = 30.0):
        import asyncio
        import time as _time
        routing = await self._get_routing_async()
        deadline = _time.monotonic() + timeout_s
        kicked = False
        while not routing["replicas"]:
            if not kicked:
                try:
                    await asyncio.wait_for(
                        self._controller().request_upscale.remote(
                            self._app, routing["deployment"]), timeout=30)
                except Exception:  # noqa: BLE001 — retried below
                    pass
                kicked = True
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"deployment {routing['deployment']!r} has no "
                    f"replicas after {timeout_s}s")
            await asyncio.sleep(0.1)
            routing = await self._get_routing_async(refresh=True)
        return routing

    async def _pick_replica_async(self):
        import asyncio
        routing = await self._get_routing_async()
        if not routing["replicas"]:
            routing = await self._wait_for_replicas_async()
        replicas = routing["replicas"]
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)

        async def _aw(ref):
            return await ref

        try:
            qa, qb = await asyncio.wait_for(
                asyncio.gather(_aw(a.num_ongoing.remote()),
                               _aw(b.num_ongoing.remote())),
                timeout=5)
        except Exception:  # noqa: BLE001 - refresh and fall back
            routing = await self._get_routing_async(refresh=True)
            if not routing["replicas"]:
                routing = await self._wait_for_replicas_async()
            return random.choice(routing["replicas"])
        return a if qa <= qb else b

    async def remote_async(self, *args, **kwargs):
        """Route + dispatch without blocking the event loop; returns the
        same DeploymentResponse/Generator as ``remote()``."""
        mux = self._mux_id
        if mux:
            import time as _time
            routing = await self._get_routing_async()
            replica = self._mux_affinity.get(mux)
            if replica is not None and replica in routing["replicas"]:
                last_ok = self._mux_probe_ok.get(replica, 0.0)
                if _time.monotonic() - last_ok > self._MUX_PROBE_TTL_S:
                    import asyncio
                    try:
                        await asyncio.wait_for(
                            replica.num_ongoing.remote(), timeout=5)
                        self._mux_probe_ok[replica] = _time.monotonic()
                    except Exception:  # noqa: BLE001 — crashed: re-pin
                        await self._get_routing_async(refresh=True)
                        self._mux_probe_ok.pop(replica, None)
                        replica = None
            else:
                replica = None
            if replica is None:
                replica = await self._pick_replica_async()
                self._mux_affinity[mux] = replica
                self._mux_probe_ok[replica] = _time.monotonic()
            return self._dispatch(replica, args, kwargs, mux)
        replica = await self._pick_replica_async()
        return self._dispatch(replica, args, kwargs)

    def remote(self, *args, **kwargs):
        mux = self._mux_id
        if mux:
            import time as _time
            routing = self._get_routing()
            replica = self._mux_affinity.get(mux)
            if replica is not None and replica in routing["replicas"]:
                # optimistic dispatch: probe only when the cached
                # liveness result is stale (ADVICE: a probe per dispatch
                # added a full RPC round trip to every request)
                last_ok = self._mux_probe_ok.get(replica, 0.0)
                if _time.monotonic() - last_ok > self._MUX_PROBE_TTL_S:
                    try:
                        ray_tpu.get(replica.num_ongoing.remote(),
                                    timeout=5)
                        self._mux_probe_ok[replica] = _time.monotonic()
                    except Exception:  # noqa: BLE001 — crashed: re-pin
                        self._get_routing(refresh=True)
                        self._mux_probe_ok.pop(replica, None)
                        replica = None
            else:
                replica = None
            if replica is None:
                replica = self._pick_replica()
                self._mux_affinity[mux] = replica
                self._mux_probe_ok[replica] = _time.monotonic()
            return self._dispatch(replica, args, kwargs, mux)
        replica = self._pick_replica()
        return self._dispatch(replica, args, kwargs)

    def __reduce__(self):
        return (DeploymentHandle, (self._app, self._deployment,
                                   self._method, self._mux_id,
                                   self._stream))

    # identity is the target, not the instance: the controller compares
    # init_args across redeploys to decide in-place reconfigure vs
    # restart, and composed apps carry handles in init_args
    def __eq__(self, other):
        return (isinstance(other, DeploymentHandle)
                and (self._app, self._deployment, self._method)
                == (other._app, other._deployment, other._method))

    def __hash__(self):
        return hash((self._app, self._deployment, self._method))
