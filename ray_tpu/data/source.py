"""Document shard sources for the streaming training data plane.

A :class:`DocumentSource` is the deterministic substrate everything
upstream of the packer stands on: ``num_shards`` ordered shards, each an
ordered list of token sequences, addressed by ``(shard, index)``.
``read(shard, start, count)`` is a **pure function** — same arguments,
same documents, every process, every time.  That purity is the whole
robustness story: a reader that dies mid-fetch is restarted and the
fetch re-issued verbatim with exactly-once semantics for free, and the
stream cursor (per-shard offsets + packer residue) pins the entire
batch sequence.

Documents carry a globally unique ``doc_id`` (``shard * stride + index``)
so the chaos fuzz can assert no-drop/no-dup sample accounting across
kills and resumes.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

Doc = Tuple[int, np.ndarray]     # (doc_id, tokens int32 [n])


class DocumentSource:
    """Base: ordered shards of ordered token documents.

    Subclasses implement :meth:`docs_in_shard` and :meth:`read`; both
    must be pure (no hidden per-process state) — the data plane
    re-issues reads after reader deaths and replays them after
    cross-process resume.
    """

    num_shards: int = 1

    def docs_in_shard(self, shard: int) -> int:
        raise NotImplementedError

    def read(self, shard: int, start: int, count: int) -> List[Doc]:
        """Documents ``[start, start+count)`` of ``shard`` (short reads
        at shard end are fine; past-the-end reads return [])."""
        raise NotImplementedError

    def doc_stride(self) -> int:
        """doc_id = shard * stride + index; stride bounds any shard."""
        return max((self.docs_in_shard(s)
                    for s in range(self.num_shards)), default=1)

    def total_docs(self) -> int:
        return sum(self.docs_in_shard(s) for s in range(self.num_shards))


class SyntheticDocs(DocumentSource):
    """Deterministic synthetic corpus: ``doc(shard, idx)`` is a pure
    function of ``(seed, shard, idx)`` — the host-sim stand-in for a
    tokenized web corpus, with variable document lengths so the packer
    has real work (padding to reclaim).

    Lengths and contents derive from a blake2b-seeded ``RandomState``
    per document, so any document is addressable without materializing
    its shard.
    """

    def __init__(self, seed: int = 0, *, num_shards: int = 4,
                 docs_per_shard: int = 64, vocab: int = 256,
                 min_len: int = 4, max_len: int = 24):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        if not (1 <= min_len <= max_len):
            raise ValueError(f"need 1 <= min_len <= max_len, got "
                             f"{min_len}..{max_len}")
        self.seed = int(seed)
        self.num_shards = int(num_shards)
        self.docs_per_shard = int(docs_per_shard)
        self.vocab = int(vocab)
        self.min_len = int(min_len)
        self.max_len = int(max_len)

    def docs_in_shard(self, shard: int) -> int:
        return self.docs_per_shard if 0 <= shard < self.num_shards else 0

    def doc_stride(self) -> int:
        return self.docs_per_shard

    def _doc(self, shard: int, idx: int) -> np.ndarray:
        h = hashlib.blake2b(
            f"{self.seed}/{shard}/{idx}".encode(), digest_size=4)
        rng = np.random.RandomState(
            int.from_bytes(h.digest(), "little"))
        n = int(rng.randint(self.min_len, self.max_len + 1))
        return rng.randint(0, self.vocab, n).astype(np.int32)

    def read(self, shard: int, start: int, count: int) -> List[Doc]:
        end = min(start + count, self.docs_in_shard(shard))
        return [(shard * self.docs_per_shard + i, self._doc(shard, i))
                for i in range(start, end)]


class TokenFileSource(DocumentSource):
    """Pre-tokenized corpus on disk: one ``.jsonl`` file per shard,
    one JSON token list per line (the layout ``write_token_shards``
    emits).  Files are read lazily per fetch — a reader actor holds no
    shard state beyond the path list, so restarting one is free."""

    def __init__(self, paths: Sequence[str]):
        if not paths:
            raise ValueError("TokenFileSource needs at least one shard "
                             "file")
        self.paths = [str(p) for p in paths]
        self.num_shards = len(self.paths)
        # byte offset of each document line, built on the shard's first
        # touch — chunked fetches then seek directly instead of
        # rescanning from line 0 (O(shard) per epoch, not O(shard^2))
        self._offsets: List[Optional[List[int]]] = \
            [None] * self.num_shards
        self._stride: Optional[int] = None

    def _shard_offsets(self, shard: int) -> List[int]:
        if self._offsets[shard] is None:
            offsets: List[int] = []
            with open(self.paths[shard], "rb") as f:
                pos = f.tell()
                for line in f:
                    if line.strip():
                        offsets.append(pos)
                    pos = f.tell()
            self._offsets[shard] = offsets
        return self._offsets[shard]

    def docs_in_shard(self, shard: int) -> int:
        if not (0 <= shard < self.num_shards):
            return 0
        return len(self._shard_offsets(shard))

    def doc_stride(self) -> int:
        # the stride (max shard size) needs every shard's count once;
        # cache it so per-fetch id assignment doesn't re-touch the
        # whole corpus (each shard file is still scanned at most once
        # per process, for its offset index)
        if self._stride is None:
            self._stride = super().doc_stride()
        return self._stride

    def read(self, shard: int, start: int, count: int) -> List[Doc]:
        stride = self.doc_stride()
        offsets = self._shard_offsets(shard)
        out: List[Doc] = []
        with open(self.paths[shard], "rb") as f:   # offsets are binary
            for idx in range(start, min(start + count, len(offsets))):
                f.seek(offsets[idx])
                toks = np.asarray(json.loads(f.readline()), np.int32)
                out.append((shard * stride + idx, toks))
        return out


def write_token_shards(directory: str, shards: Sequence[Sequence[Sequence[int]]]
                       ) -> List[str]:
    """Write ``shards`` (list of shards, each a list of token lists) as
    ``shard_NNN.jsonl`` files; returns the paths for
    :class:`TokenFileSource`."""
    os.makedirs(directory, exist_ok=True)
    paths = []
    for s, docs in enumerate(shards):
        p = os.path.join(directory, f"shard_{s:03d}.jsonl")
        with open(p, "w") as f:
            for doc in docs:
                f.write(json.dumps([int(t) for t in doc]) + "\n")
        paths.append(p)
    return paths
