"""Blocks — the unit of data movement.

Parity: ``python/ray/data/block.py``.  A block is a ``pyarrow.Table``
(host memory, zero-copied through the shm object store); the
BlockAccessor converts between formats and slices batches.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table


def _to_table(data: Any) -> pa.Table:
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, dict):
        cols = {}
        for k, v in data.items():
            arr = np.asarray(v)
            if arr.ndim > 1:
                # tensor column: store as fixed-size list
                flat = arr.reshape(len(arr), -1)
                cols[k] = pa.FixedSizeListArray.from_arrays(
                    pa.array(flat.ravel()), flat.shape[1])
            else:
                cols[k] = pa.array(arr)
        return pa.table(cols)
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:
        pass
    if isinstance(data, list):
        if data and isinstance(data[0], dict):
            return pa.Table.from_pylist(data)
        return pa.table({"item": pa.array(data)})
    raise TypeError(f"cannot convert {type(data)} to a block")


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(_to_table(block))

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self):
        return self.block.schema

    def to_arrow(self) -> pa.Table:
        return self.block

    def to_pandas(self):
        return self.block.to_pandas()

    def to_numpy(self, columns: Optional[List[str]] = None
                 ) -> Dict[str, np.ndarray]:
        cols = columns or self.block.column_names
        out = {}
        for name in cols:
            col = self.block.column(name)
            if pa.types.is_fixed_size_list(col.type):
                width = col.type.list_size
                combined = col.combine_chunks()
                flat = combined.flatten().to_numpy(zero_copy_only=False)
                out[name] = flat.reshape(-1, width)
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def to_pylist(self) -> List[Dict[str, Any]]:
        return self.block.to_pylist()

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    def take_rows(self, indices) -> Block:
        return self.block.take(pa.array(indices))

    def iter_batches(self, batch_size: Optional[int],
                     batch_format: str = "numpy") -> Iterator[Any]:
        n = self.num_rows()
        if batch_size is None or batch_size >= n:
            ranges = [(0, n)] if n else []
        else:
            ranges = [(i, min(i + batch_size, n))
                      for i in range(0, n, batch_size)]
        for start, end in ranges:
            chunk = BlockAccessor(self.slice(start, end))
            yield format_batch(chunk.block, batch_format)


def format_batch(block: Block, batch_format: str):
    acc = BlockAccessor(block)
    if batch_format in ("numpy", "default", None):
        return acc.to_numpy()
    if batch_format == "pandas":
        return acc.to_pandas()
    if batch_format in ("pyarrow", "arrow"):
        return block
    raise ValueError(f"unknown batch_format {batch_format!r}")


def batch_to_block(batch: Any) -> Block:
    return _to_table(batch)


def concat_blocks(blocks: List[Block]) -> Block:
    tables = [b for b in blocks if b.num_rows > 0]
    if not tables:
        return blocks[0] if blocks else pa.table({})
    return pa.concat_tables(tables, promote_options="default")
