"""DataContext — process-wide execution options for Datasets.

Parity role: ``python/ray/data/context.py`` (DataContext) — the knobs
the streaming executor reads at operator-construction time.  Thread
through ``DataContext.get_current()``; tests and jobs mutate the
singleton.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class DataContext:
    # per-operator cap on OUTSTANDING bytes (in-flight task outputs +
    # completed-but-unreleased buffer).  None = task-count budgets only.
    # (reference: backpressure_policy/concurrency_cap + the resource
    # manager's per-op memory budgets)
    op_bytes_budget: Optional[int] = None
    # default per-operator in-flight task cap
    op_task_budget: int = 8

    _current: "Optional[DataContext]" = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = cls()
        return cls._current
