"""``ray_tpu.data`` — distributed datasets (parity: ``ray.data``) plus
the streaming training data plane (shard-reader actors, sample packing,
the deterministic preemption-proof stream cursor — ``stream.py``)."""

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.config import DataConfig, data_config
from ray_tpu.data.connectors import (from_huggingface, from_torch,
                                     read_sql, read_webdataset)
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.io_extra import range_tensor, read_tfrecords
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.packer import PackedBatch, SamplePacker
from ray_tpu.data.read_api import (from_arrow, from_items, from_numpy,
                                   from_pandas, range, read_binary_files,
                                   read_csv, read_images, read_json,
                                   read_numpy, read_parquet, read_text)
from ray_tpu.data.source import (DocumentSource, SyntheticDocs,
                                 TokenFileSource, write_token_shards)
from ray_tpu.data.stream import (DataPlaneError, StreamBatch,
                                 StreamCursor, StreamingLoader)

__all__ = [
    "Block", "BlockAccessor", "DataContext", "Dataset", "DataIterator",
    "GroupedData",
    "range", "range_tensor",
    "from_items", "from_numpy", "from_arrow", "from_pandas",
    "from_torch", "from_huggingface",
    "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_numpy", "read_images",
    "read_webdataset", "read_sql", "read_tfrecords",
    # streaming training data plane
    "DataConfig", "data_config",
    "DocumentSource", "SyntheticDocs", "TokenFileSource",
    "write_token_shards",
    "SamplePacker", "PackedBatch",
    "StreamCursor", "StreamBatch", "StreamingLoader", "DataPlaneError",
]
