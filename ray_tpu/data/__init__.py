"""``ray_tpu.data`` — distributed datasets (parity: ``ray.data``)."""

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.connectors import (from_huggingface, from_torch,
                                     read_sql, read_webdataset)
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.io_extra import range_tensor, read_tfrecords
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (from_arrow, from_items, from_numpy,
                                   from_pandas, range, read_binary_files,
                                   read_csv, read_images, read_json,
                                   read_numpy, read_parquet, read_text)

__all__ = [
    "Block", "BlockAccessor", "DataContext", "Dataset", "DataIterator",
    "GroupedData",
    "range", "range_tensor",
    "from_items", "from_numpy", "from_arrow", "from_pandas",
    "from_torch", "from_huggingface",
    "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_numpy", "read_images",
    "read_webdataset", "read_sql", "read_tfrecords",
]
