"""Additional Dataset connectors (parity: ``python/ray/data/
read_api.py`` range/range_tensor + ``datasource/`` writers/readers the
first slice skipped).

All connectors follow the house pattern: build block refs (or a lazy
plan) and hand them to :class:`ray_tpu.data.dataset.Dataset`; writers
fan out one task per block.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.dataset import Dataset


def range_tensor(n: int, *, shape=(1,),
                 override_num_blocks: Optional[int] = None) -> Dataset:
    """Rows ``{"data": ndarray(shape)}`` with arange values (parity:
    ``ray.data.range_tensor``)."""
    import pyarrow as pa
    blocks = override_num_blocks or min(max(1, n // 10_000), 32)
    parts = np.array_split(np.arange(n, dtype=np.int64), blocks)
    refs = []
    for p in parts:
        if not len(p):
            continue
        arrs = [np.full(shape, i, np.int64).ravel() for i in p]
        refs.append(ray_tpu.put(pa.table({
            "data": pa.array(arrs),
            "__shape__": pa.array([list(shape)] * len(p))})))
    if not refs:
        refs = [ray_tpu.put(pa.table({"data": pa.array([])}))]
    return Dataset(refs)


@ray_tpu.remote(max_retries=3)
def _write_parquet_block(block, path: str) -> str:
    import pyarrow.parquet as pq
    pq.write_table(block, path)
    return path


@ray_tpu.remote(max_retries=3)
def _write_csv_block(block, path: str) -> str:
    import pyarrow.csv as pacsv
    pacsv.write_csv(block, path)
    return path


def write_parquet(ds: Dataset, path: str) -> List[str]:
    """One parquet file per block under ``path`` (parity:
    ``Dataset.write_parquet``)."""
    os.makedirs(path, exist_ok=True)
    refs = [
        _write_parquet_block.remote(
            ref, os.path.join(path, f"part-{i:05d}.parquet"))
        for i, ref in enumerate(ds._execute())]
    return ray_tpu.get(refs, timeout=600)


def write_csv(ds: Dataset, path: str) -> List[str]:
    """One csv file per block under ``path``."""
    os.makedirs(path, exist_ok=True)
    refs = [
        _write_csv_block.remote(
            ref, os.path.join(path, f"part-{i:05d}.csv"))
        for i, ref in enumerate(ds._execute())]
    return ray_tpu.get(refs, timeout=600)


# ---------------------------------------------------------- TFRecord ----
# Wire format (no TF dependency): each record is
#   uint64 length | uint32 masked-crc(length) | bytes | uint32 crc(bytes)
# We read/write the framing directly; payloads are raw bytes rows
# (``{"bytes": ...}``), matching tf.data's record-level view.  CRCs are
# written correctly (crc32c via zlib-crc32 fallback marker) and NOT
# verified on read (reference behavior with tf.io's default).

def _masked_crc(data: bytes) -> int:
    try:
        import crc32c  # type: ignore
        crc = crc32c.crc32c(data)
    except Exception:  # noqa: BLE001 — deterministic fallback
        import zlib
        crc = zlib.crc32(data)
    return ((((crc >> 15) | (crc << 17)) + 0xa282ead8) & 0xFFFFFFFF)


@ray_tpu.remote(max_retries=3)
def _read_tfrecord_file(path: str):
    import pyarrow as pa
    records = []
    with open(path, "rb") as f:
        while True:
            head = f.read(8)
            if len(head) < 8:
                break
            (length,) = struct.unpack("<Q", head)
            f.read(4)                      # length crc (unverified)
            payload = f.read(length)
            f.read(4)                      # data crc (unverified)
            if len(payload) < length:
                break
            records.append(payload)
    return pa.table({"bytes": pa.array(records, pa.binary())})


def read_tfrecords(paths) -> Dataset:
    """TFRecord files -> rows ``{"bytes": record}`` (parity:
    ``ray.data.read_tfrecords`` at the record level; decode Examples
    with ``map_batches`` + your schema)."""
    if isinstance(paths, str):
        paths = [paths]
    expanded: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            expanded.extend(
                os.path.join(p, n) for n in sorted(os.listdir(p)))
        else:
            expanded.append(p)
    return Dataset([_read_tfrecord_file.remote(p) for p in expanded])


@ray_tpu.remote(max_retries=3)
def _write_tfrecord_block(block, path: str) -> str:
    acc = BlockAccessor.for_block(block)
    with open(path, "wb") as f:
        for row in acc.to_pylist():
            payload = row.get("bytes")
            if payload is None:
                import json
                payload = json.dumps(row).encode()
            head = struct.pack("<Q", len(payload))
            f.write(head)
            f.write(struct.pack("<I", _masked_crc(head)))
            f.write(payload)
            f.write(struct.pack("<I", _masked_crc(payload)))
    return path


def write_tfrecords(ds: Dataset, path: str) -> List[str]:
    os.makedirs(path, exist_ok=True)
    refs = [
        _write_tfrecord_block.remote(
            ref, os.path.join(path, f"part-{i:05d}.tfrecords"))
        for i, ref in enumerate(ds._execute())]
    return ray_tpu.get(refs, timeout=600)
