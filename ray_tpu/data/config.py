"""Streaming-data-plane env knobs — the single home for input-pipeline
config.

Follows the ``resilience_config()`` / ``rl_config()`` precedent: one
frozen dataclass resolved from the environment once, ``refresh=True``
for tests and A/B drivers that flip flags after import.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Input-pipeline knobs, resolved once from the environment.

    - ``RAY_TPU_DATA_PREFETCH`` (default ``2``): bounded prefetch-queue
      depth in batches between the packer and the trainer.  The
      producer thread blocks when the queue is full — backpressure by
      construction, an unbounded queue would convert a slow trainer
      into unbounded host memory.
    - ``RAY_TPU_DATA_READERS`` (default ``0``): shard-reader actor
      replicas.  ``0`` reads shards in-process on the producer thread
      (host-sim/tests); ``>= 1`` spawns that many restartable reader
      actors (needs an initialized ray_tpu session).
    - ``RAY_TPU_DATA_RETRIES`` (default ``3``): reader-restart /
      pack-retry budget per fetch.  A read that keeps failing past it
      raises a typed :class:`~ray_tpu.data.stream.DataPlaneError`
      instead of spinning forever.
    - ``RAY_TPU_DATA_PACK`` (default ``1``): sample packing — fill each
      ``[B, S]`` row with multiple documents under segment-aware
      attention masking, reclaiming padding FLOPs.  ``0`` gives every
      document its own row (pad-to-S), the unpacked A/B arm.
    - ``RAY_TPU_DATA_READ_TIMEOUT`` (default ``120``): seconds a
      reader-actor fetch may take before it counts as failed (the
      reader is restarted and the fetch re-issued against the retry
      budget).  Raise it for cold/slow shard storage — a healthy slow
      fetch must not be converted into restarts.
    - ``RAY_TPU_DATA_HEDGE`` (default ``0`` = off): shard-read hedge
      budget in seconds — a read that has not returned within it is
      re-issued to a standby reader, first response wins (the loser's
      identical result is discarded; exactly-once holds because
      sources are pure and only the cursor advances consumption).
      The gray-failure mitigation for the slow-but-alive shard.
    - ``RAY_TPU_DATA_STALL_S`` (default ``0.2``): **deprecated alias**
      — seconds a bare ``data.stall@N`` chaos entry sleeps inside a
      shard read.  Superseded by the unified ``site@N:delay=S`` /
      ``site@N..M:delay=S`` latency grammar (``util/chaos.py``),
      which needs no side-channel knob; kept so old specs replay.
    """
    prefetch: int = 2
    readers: int = 0
    retries: int = 3
    pack: bool = True
    read_timeout_s: float = 120.0
    hedge_s: float = 0.0
    stall_s: float = 0.2


_CONFIG: Optional[DataConfig] = None


def data_config(refresh: bool = False) -> DataConfig:
    """The process-wide :class:`DataConfig` (env read once, cached)."""
    global _CONFIG
    if _CONFIG is None or refresh:
        env = os.environ.get
        prefetch = int(env("RAY_TPU_DATA_PREFETCH", "2"))
        if prefetch < 1:
            print(f"RAY_TPU_DATA_PREFETCH={prefetch} must be >= 1 "
                  "(the trainer needs at least one staged batch); "
                  "using 1", file=sys.stderr)
            prefetch = 1
        readers = int(env("RAY_TPU_DATA_READERS", "0"))
        if readers < 0:
            print(f"RAY_TPU_DATA_READERS={readers} negative; using 0 "
                  "(in-process reads)", file=sys.stderr)
            readers = 0
        retries = int(env("RAY_TPU_DATA_RETRIES", "3"))
        if retries < 0:
            print(f"RAY_TPU_DATA_RETRIES={retries} negative; using 0 "
                  "(fail on the first error)", file=sys.stderr)
            retries = 0
        _CONFIG = DataConfig(
            prefetch=prefetch,
            readers=readers,
            retries=retries,
            pack=env("RAY_TPU_DATA_PACK", "1") != "0",
            read_timeout_s=float(env("RAY_TPU_DATA_READ_TIMEOUT",
                                     "120")),
            hedge_s=max(0.0, float(env("RAY_TPU_DATA_HEDGE", "0"))),
            stall_s=float(env("RAY_TPU_DATA_STALL_S", "0.2")),
        )
    return _CONFIG
