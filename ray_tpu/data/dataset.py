"""Dataset — lazy, distributed data pipelines.

Parity: ``python/ray/data/dataset.py`` (``Dataset``): a logical plan of
operators over blocks; execution fans out ray_tpu tasks per block with a
bounded in-flight window (streaming backpressure, the shape of the
reference's ``StreamingExecutor``).  Blocks are pyarrow tables in the shm
object store; ``iter_batches`` feeds accelerators from host blocks.
"""

from __future__ import annotations

import itertools
from typing import (Any, Callable, Dict, Iterator, List, Optional, Tuple,
                    Union)

import numpy as np

import ray_tpu
from ray_tpu.data.block import (Block, BlockAccessor, batch_to_block,
                                concat_blocks, format_batch)
from ray_tpu.object_ref import ObjectRef

# bounded number of concurrently materializing blocks (backpressure)
DEFAULT_WINDOW = 8


# ---------------------------------------------------------------- remote ops
@ray_tpu.remote(max_retries=3)
def _map_block(block: Block, fns) -> Block:
    for kind, fn, kwargs in fns:
        acc = BlockAccessor.for_block(block)
        if kind == "map_batches":
            batch_size = kwargs.get("batch_size")
            fmt = kwargs.get("batch_format", "numpy")
            out = []
            for batch in acc.iter_batches(batch_size, fmt):
                res = fn(batch)
                out.append(batch_to_block(res))
            block = concat_blocks(out) if out else block.slice(0, 0)
        elif kind == "map":
            rows = [fn(r) for r in acc.to_pylist()]
            block = batch_to_block(rows)
        elif kind == "flat_map":
            rows = list(itertools.chain.from_iterable(
                fn(r) for r in acc.to_pylist()))
            block = batch_to_block(rows) if rows else block.slice(0, 0)
        elif kind == "filter":
            rows = [r for r in acc.to_pylist() if fn(r)]
            block = batch_to_block(rows) if rows else block.slice(0, 0)
        else:
            raise ValueError(kind)
    return block


@ray_tpu.remote(max_retries=3)
def _split_block(block: Block, n: int, seed: Optional[int]) -> List[Block]:
    """Split one block into n shards (for shuffle/repartition)."""
    acc = BlockAccessor.for_block(block)
    rows = acc.num_rows()
    idx = np.arange(rows)
    if seed is not None:
        rng = np.random.default_rng(seed)
        rng.shuffle(idx)
    parts = np.array_split(idx, n)
    return [acc.take_rows(p) if len(p) else block.slice(0, 0)
            for p in parts]


@ray_tpu.remote(max_retries=3)
def _merge_blocks(*blocks: Block) -> Block:
    # with num_returns=1 an upstream _split_block resolves to the whole
    # 1-element list rather than its only item — flatten
    flat: List[Block] = []
    for b in blocks:
        flat.extend(b) if isinstance(b, list) else flat.append(b)
    return concat_blocks(flat)


# ------------------------------------------------------------------- plan
class _Op:
    pass


class _MapOp(_Op):
    def __init__(self, kind: str, fn: Callable, **kwargs):
        self.kind = kind
        self.fn = fn
        self.kwargs = kwargs


class _ActorMapOp(_Op):
    def __init__(self, cls, *, pool_size: int, batch_size, batch_format,
                 fn_constructor_args=None, fn_constructor_kwargs=None):
        self.cls = cls
        self.pool_size = pool_size
        self.batch_size = batch_size
        self.batch_format = batch_format
        self.fn_constructor_args = fn_constructor_args
        self.fn_constructor_kwargs = fn_constructor_kwargs


class _AllToAllOp(_Op):
    def __init__(self, kind: str, **kwargs):
        self.kind = kind
        self.kwargs = kwargs


def _all_to_all_refs(refs_in: List[ObjectRef], kind: str,
                     arg: Dict[str, Any]) -> List[ObjectRef]:
    """Fan out one all-to-all stage over materialized upstream refs."""
    if kind == "shuffle":
        seed = arg.get("seed")
        n = max(1, len(refs_in))
        parts = _fan_out([_split_block.options(num_returns=n).remote(
            r, n, (seed + i) if seed is not None else None)
            for i, r in enumerate(refs_in)])
        return [_merge_blocks.remote(
            *[parts[j][i] for j in range(len(refs_in))])
            for i in range(n)]
    if kind == "repartition":
        n = arg["num_blocks"]
        parts = _fan_out([_split_block.options(num_returns=n).remote(
            r, n, None) for r in refs_in])
        return [_merge_blocks.remote(
            *[parts[j][i] for j in range(len(refs_in))])
            for i in range(n)]
    if kind == "sort":
        return _distributed_sort(refs_in, arg["key"], arg["descending"])
    raise ValueError(kind)


def _fan_out(parts: List) -> List[List]:
    """num_returns>1 task handles resolve to either a list of refs or a
    single ref (n==1); normalize to list-of-lists."""
    return [p if isinstance(p, list) else [p] for p in parts]


@ray_tpu.remote(max_retries=3)
def _sample_keys(block: Block, key: str, n: int):
    """Uniform key sample from one block (boundary estimation; nulls
    are excluded — they route to the last partition)."""
    col = block.column(key).drop_null().to_numpy(zero_copy_only=False)
    if len(col) == 0:
        return col
    idx = np.random.default_rng(0).integers(0, len(col),
                                            size=min(n, len(col)))
    return col[idx]


@ray_tpu.remote(max_retries=3)
def _range_partition(block: Block, key: str, boundaries,
                     descending: bool) -> List[Block]:
    """Split one block into len(boundaries)+1 key ranges."""
    import pyarrow as pa
    import pyarrow.compute as pc
    chunked = block.column(key)
    null_mask = np.asarray(pc.is_null(chunked).combine_chunks())
    # searchsorted can't order None: substitute the first boundary, then
    # force nulls into the last partition (pyarrow sorts nulls at_end)
    col = np.asarray(chunked.fill_null(boundaries[0]).to_numpy(
        zero_copy_only=False))
    part = np.searchsorted(boundaries, col, side="right")
    if descending:
        part = len(boundaries) - part
    part = np.where(null_mask, len(boundaries), part)
    out = []
    for p in range(len(boundaries) + 1):
        mask = part == p
        out.append(block.filter(pa.array(mask)) if mask.any()
                   else block.slice(0, 0))
    return out


@ray_tpu.remote(max_retries=3)
def _merge_sorted(key: str, descending: bool, *parts: Block) -> Block:
    import pyarrow.compute as pc
    flat: List[Block] = []
    for p in parts:
        flat.extend(p) if isinstance(p, list) else flat.append(p)
    table = concat_blocks(flat)
    order = "descending" if descending else "ascending"
    return table.take(pc.sort_indices(table, sort_keys=[(key, order)]))


def _distributed_sort(refs_in: List[ObjectRef], key: str,
                      descending: bool) -> List[ObjectRef]:
    """Sample sort (parity: ray.data push-based shuffle sort): sample
    keys -> pick partition boundaries -> range-partition every block in
    parallel -> sort each partition in parallel.  Output block i holds
    keys <= block i+1 (or >= when descending); nothing funnels through
    the driver except the O(blocks * sample) key sample."""
    if not refs_in:
        return []
    n = len(refs_in)
    if n == 1:
        return [_merge_sorted.remote(key, descending, refs_in[0])]
    samples = np.concatenate(
        ray_tpu.get([_sample_keys.remote(r, key, 64) for r in refs_in],
                    timeout=600))
    if len(samples) == 0:
        return list(refs_in)
    # boundaries by rank in the sorted sample (not np.quantile: no
    # interpolation, so string/datetime keys sort too)
    srt = np.sort(samples)
    boundaries = srt[(np.arange(1, n) * len(srt)) // n]
    parts = _fan_out([_range_partition.options(num_returns=n).remote(
        r, key, boundaries, descending) for r in refs_in])
    return [_merge_sorted.remote(key, descending,
                                 *[parts[j][i] for j in range(n)])
            for i in range(n)]


def iter_fixed_batches(block_iter: Iterator[Block], *,
                       batch_size: Optional[int], batch_format: str,
                       drop_last: bool) -> Iterator[Any]:
    """Fixed-size batches over a block stream: remainder rows carry
    into the next block, so batch shapes stay constant across block
    boundaries (jit-compiled train steps need static shapes).  Shared
    by ``Dataset.iter_batches`` and ``DataIterator.iter_batches``."""
    carry: Optional[Block] = None
    for block in block_iter:
        if carry is not None and carry.num_rows > 0:
            block = concat_blocks([carry, block])
            carry = None
        acc = BlockAccessor.for_block(block)
        n = acc.num_rows()
        if batch_size is None:
            if n:
                yield format_batch(block, batch_format)
            continue
        start = 0
        while n - start >= batch_size:
            yield format_batch(acc.slice(start, start + batch_size),
                               batch_format)
            start += batch_size
        if start < n:
            carry = acc.slice(start, n)
    if carry is not None and carry.num_rows > 0 and not drop_last:
        yield format_batch(carry, batch_format)


def iter_device_batches(batch_iter: Iterator[Any], *, sharding=None,
                        prefetch: int = 2) -> Iterator[Any]:
    """Async ``device_put`` pipeline: keeps ``prefetch`` device batches
    in flight so H2D transfer overlaps the consumer's compute."""
    import jax

    def put(batch):
        if sharding is None:
            return jax.tree.map(jax.numpy.asarray, batch)
        return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)

    from collections import deque
    window: deque = deque()
    for batch in batch_iter:
        window.append(put(batch))
        if len(window) > prefetch:
            yield window.popleft()
    while window:
        yield window.popleft()


class Dataset:
    def __init__(self, block_refs: List[ObjectRef],
                 ops: Optional[List[_Op]] = None):
        self._block_refs = block_refs
        self._ops: List[_Op] = ops or []

    # -------------------------------------------------------- transforms
    def _with_op(self, op: _Op) -> "Dataset":
        return Dataset(self._block_refs, self._ops + [op])

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    concurrency: Optional[int] = None,
                    compute=None, fn_constructor_args=None,
                    fn_constructor_kwargs=None, **ignored) -> "Dataset":
        """Map a function — or a *callable class* — over batches.

        A class UDF runs on a fixed actor pool (constructed once per
        actor; ``concurrency`` = pool size), the reference's
        ``ActorPoolStrategy`` (``actor_pool_map_operator.py:1``).
        """
        if isinstance(fn, type):
            pool = concurrency or getattr(compute, "size", None) or 2
            if isinstance(pool, (tuple, list)):  # Ray's (min, max) form
                pool = pool[-1]
            return self._with_op(_ActorMapOp(
                fn, pool_size=int(pool), batch_size=batch_size,
                batch_format=batch_format,
                fn_constructor_args=fn_constructor_args,
                fn_constructor_kwargs=fn_constructor_kwargs))
        return self._with_op(_MapOp("map_batches", fn,
                                    batch_size=batch_size,
                                    batch_format=batch_format))

    def map(self, fn: Callable) -> "Dataset":
        return self._with_op(_MapOp("map", fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with_op(_MapOp("flat_map", fn))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with_op(_MapOp("filter", fn))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(batch):
            batch[name] = fn(batch)
            return batch
        return self.map_batches(add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}
        return self.map_batches(drop)

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(batch):
            return {k: batch[k] for k in cols}
        return self.map_batches(select)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with_op(_AllToAllOp("shuffle", seed=seed))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with_op(_AllToAllOp("repartition",
                                         num_blocks=num_blocks))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with_op(_AllToAllOp("sort", key=key,
                                         descending=descending))

    def union(self, other: "Dataset") -> "Dataset":
        """Lazy concatenation: both branches keep their own logical
        plans and stream at consumption time — nothing materializes
        (parity: the reference keeps Union in the logical plan,
        ``data/_internal/logical/operators/n_ary_operator.py``)."""
        return _UnionDataset([self, other])

    def limit(self, n: int) -> "Dataset":
        """Lazy prefix: consumption stops pulling upstream blocks once
        ``n`` rows are out — a limit over an expensive pipeline never
        runs the whole thing (parity: lazy Limit in the logical plan,
        ``one_to_one_operator.py``)."""
        return _LimitDataset(self, n)

    def zip(self, other: "Dataset") -> "Dataset":
        import pyarrow as pa
        a = self.materialize()._to_table()
        b = other.materialize()._to_table()
        if a.num_rows != b.num_rows:
            raise ValueError("zip requires equal row counts")
        cols = {name: a.column(name) for name in a.column_names}
        for name in b.column_names:
            key = name if name not in cols else f"{name}_1"
            cols[key] = b.column(name)
        from ray_tpu.data import from_arrow
        return from_arrow(pa.table(cols))

    # --------------------------------------------------------- execution
    def _build_operators(self, window: int):
        """Fuse consecutive function-map ops; class UDFs and all-to-alls
        become their own physical operators."""
        from ray_tpu.data.streaming_executor import (ActorPoolMapOperator,
                                                     AllToAllOperator,
                                                     MapOperator)
        physical = []
        fused: List[Tuple[str, Callable, Dict]] = []

        def flush():
            nonlocal fused
            if fused:
                physical.append(MapOperator(fused, budget=window))
                fused = []

        from ray_tpu.data.streaming_executor import ShuffleOperator
        for op in self._ops:
            if isinstance(op, _MapOp):
                fused.append((op.kind, op.fn, op.kwargs))
            elif isinstance(op, _ActorMapOp):
                flush()
                physical.append(ActorPoolMapOperator(
                    op.cls, pool_size=op.pool_size,
                    fn_constructor_args=op.fn_constructor_args,
                    fn_constructor_kwargs=op.fn_constructor_kwargs,
                    batch_size=op.batch_size,
                    batch_format=op.batch_format))
            elif isinstance(op, _AllToAllOp) and op.kind == "shuffle":
                flush()
                # streaming split stage: overlaps with upstream maps
                physical.append(ShuffleOperator(
                    seed=op.kwargs.get("seed"), budget=window))
            else:
                flush()
                physical.append(AllToAllOperator(op.kind, op.kwargs))
        flush()
        return physical

    def _execute(self, window: int = DEFAULT_WINDOW
                 ) -> Iterator[ObjectRef]:
        """Stream transformed block refs through the operator DAG with
        per-operator in-flight budgets (``streaming_executor.py``)."""
        from ray_tpu.data.streaming_executor import StreamingExecutor
        executor = StreamingExecutor(self._build_operators(window))
        yield from executor.execute(list(self._block_refs))

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        """Split one streaming execution across ``n`` consumers.

        Each returned :class:`DataIterator` yields a disjoint subset of
        the stream's blocks (greedy pull by default, strict round-robin
        with ``equal=True``) — the multi-worker Train ingest path
        (parity: ``Dataset.streaming_split`` /
        ``operators/output_splitter.py``).
        """
        import cloudpickle

        from ray_tpu.data.iterator import (DataIterator,
                                           _CoordinatorOwner,
                                           _SplitCoordinator)
        coord = _SplitCoordinator.remote(cloudpickle.dumps(self), n,
                                         equal)
        owner = _CoordinatorOwner(coord, dataset=self)
        iterators = [DataIterator(coord, i) for i in range(n)]
        for it in iterators:
            it._owner = owner     # coordinator dies with the last one
        return iterators

    def materialize(self) -> "Dataset":
        refs = list(self._execute())
        # force completion (and surface errors) before declaring it
        ray_tpu.wait(refs, num_returns=len(refs), timeout=600) \
            if refs else None
        return Dataset(refs)

    def _to_table(self):
        blocks = ray_tpu.get(list(self._execute()), timeout=600)
        return concat_blocks(blocks)

    # ------------------------------------------------------- consumption
    def _iter_blocks_prefetched(self, prefetch_blocks: int
                                ) -> Iterator[Block]:
        """Materialize blocks on a background thread, ``prefetch_blocks``
        ahead of the consumer (overlaps host fetch with accelerator
        compute — reference ``iter_batches`` prefetching)."""
        import queue
        import threading

        if prefetch_blocks <= 0:
            for ref in self._execute():
                yield ray_tpu.get(ref, timeout=600)
            return
        q: "queue.Queue" = queue.Queue(maxsize=prefetch_blocks)
        _END, _ERR = object(), object()
        stop = threading.Event()

        def feeder():
            gen = self._execute()
            try:
                for ref in gen:
                    block = ray_tpu.get(ref, timeout=600)
                    while not stop.is_set():
                        try:
                            q.put(block, timeout=0.2)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
                q.put(_END)
            except BaseException as e:  # noqa: BLE001 — reraised below
                if not stop.is_set():
                    q.put((_ERR, e))
            finally:
                gen.close()  # runs the executor's shutdown (actor pools)

        t = threading.Thread(target=feeder, daemon=True,
                             name="data-prefetch")
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] is _ERR:
                    raise item[1]
                yield item
        finally:
            # consumer abandoned the iterator: unblock + stop the feeder
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=30)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     prefetch_blocks: int = 2) -> Iterator[Any]:
        yield from iter_fixed_batches(
            self._iter_blocks_prefetched(prefetch_blocks),
            batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last)

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         sharding=None, drop_last: bool = True,
                         prefetch: int = 2,
                         batch_format: str = "numpy") -> Iterator[Any]:
        """``iter_batches`` that lands each batch on device ahead of the
        consumer — host decode, H2D transfer, and accelerator compute
        overlap (the TPU input-pipeline pattern; reference parity:
        ``iter_torch_batches(device=...)``).

        ``sharding``: a ``jax.sharding.Sharding`` (e.g.
        ``NamedSharding(mesh, P('dp'))``) applied to every array;
        defaults to the default device.  With a sharded batch axis,
        every batch must divide the axis size — hence ``drop_last``
        defaults to True here (unlike ``iter_batches``): a trailing
        partial batch would fail to shard.
        """
        import jax

        if batch_format != "numpy":
            raise ValueError(
                "iter_jax_batches requires batch_format='numpy' "
                "(pandas/pyarrow batches are not jax pytrees)")

        it = self.iter_batches(batch_size=batch_size,
                               batch_format=batch_format,
                               drop_last=drop_last,
                               prefetch_blocks=prefetch)
        yield from iter_device_batches(it, sharding=sharding,
                                       prefetch=prefetch)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self._execute():
            block = ray_tpu.get(ref, timeout=600)
            yield from BlockAccessor.for_block(block).to_pylist()

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        return sum(BlockAccessor.for_block(b).num_rows()
                   for b in ray_tpu.get(list(self._execute()),
                                        timeout=600))

    def schema(self):
        for ref in self._execute():
            block = ray_tpu.get(ref, timeout=600)
            return BlockAccessor.for_block(block).schema()
        return None

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def to_pandas(self):
        return self._to_table().to_pandas()

    def to_arrow(self):
        return self._to_table()

    # ------------------------------------------------------- aggregation
    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def _agg(self, col: Optional[str], how: str):
        import pyarrow.compute as pc
        table = self._to_table()
        if col is None:
            col = table.column_names[0]
        fn = {"sum": pc.sum, "min": pc.min, "max": pc.max,
              "mean": pc.mean, "count": pc.count}[how]
        return fn(table.column(col)).as_py()

    def sum(self, col: Optional[str] = None):
        return self._agg(col, "sum")

    def min(self, col: Optional[str] = None):
        return self._agg(col, "min")

    def max(self, col: Optional[str] = None):
        return self._agg(col, "max")

    def mean(self, col: Optional[str] = None):
        return self._agg(col, "mean")

    # ------------------------------------------------------------ split
    def split(self, n: int, *, equal: bool = True) -> List["Dataset"]:
        """Split into n datasets (Train ingest sharding)."""
        table = self._to_table()
        rows = table.num_rows
        bounds = np.linspace(0, rows, n + 1).astype(int)
        out = []
        for i in range(n):
            shard = table.slice(bounds[i], bounds[i + 1] - bounds[i])
            out.append(Dataset([ray_tpu.put(shard)]))
        return out

    def train_test_split(self, test_size: float = 0.25,
                         shuffle: bool = False,
                         seed: Optional[int] = None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        table = ds._to_table()
        n_test = int(table.num_rows * test_size)
        n_train = table.num_rows - n_test
        return (Dataset([ray_tpu.put(table.slice(0, n_train))]),
                Dataset([ray_tpu.put(table.slice(n_train, n_test))]))

    # ------------------------------------------------------------ write
    # Writers fan out one task per block (reference: datasink write
    # tasks), so a wide dataset writes in parallel instead of pulling
    # every block through the driver.
    def write_parquet(self, path: str) -> None:
        from ray_tpu.data.io_extra import write_parquet
        write_parquet(self, path)

    def write_csv(self, path: str) -> None:
        from ray_tpu.data.io_extra import write_csv
        write_csv(self, path)

    def write_tfrecords(self, path: str) -> None:
        from ray_tpu.data.io_extra import write_tfrecords
        write_tfrecords(self, path)

    def write_json(self, path: str) -> None:
        from ray_tpu.data.connectors import write_json
        write_json(self, path)

    def write_numpy(self, path: str, column: str) -> None:
        from ray_tpu.data.connectors import write_numpy
        write_numpy(self, path, column)

    def write_webdataset(self, path: str) -> None:
        from ray_tpu.data.connectors import write_webdataset
        write_webdataset(self, path)

    def __repr__(self):
        return (f"Dataset(num_blocks={len(self._block_refs)}, "
                f"ops={len(self._ops)})")


class GroupedData:
    def __init__(self, ds: Dataset, key: str):
        self.ds = ds
        self.key = key

    def _agg(self, col: str, how: str) -> Dataset:
        table = self.ds._to_table()
        out = table.group_by(self.key).aggregate([(col, how)])
        return Dataset([ray_tpu.put(out)])

    def sum(self, col: str) -> Dataset:
        return self._agg(col, "sum")

    def min(self, col: str) -> Dataset:
        return self._agg(col, "min")

    def max(self, col: str) -> Dataset:
        return self._agg(col, "max")

    def mean(self, col: str) -> Dataset:
        return self._agg(col, "mean")

    def count(self) -> Dataset:
        table = self.ds._to_table()
        out = table.group_by(self.key).aggregate([([], "count_all")])
        return Dataset([ray_tpu.put(out)])

    def map_groups(self, fn: Callable,
                   batch_format: str = "numpy") -> Dataset:
        import pyarrow.compute as pc
        table = self.ds._to_table()
        keys = pc.unique(table.column(self.key))
        blocks = []
        for k in keys:
            mask = pc.equal(table.column(self.key), k)
            group = table.filter(mask)
            res = fn(format_batch(group, batch_format))
            blocks.append(ray_tpu.put(batch_to_block(res)))
        return Dataset(blocks)


# ------------------------------------------------------ lazy set ops
class _StreamSourceDataset(Dataset):
    """A plan whose *input blocks* are another dataset's output stream.

    Used where an op needs the full logical row set of a composite
    source (all-to-all after a union, transforms after a limit): the
    source still streams block-by-block, but this plan's operators see
    one unified input list, preserving global semantics."""

    def __init__(self, source: Dataset, ops: Optional[List[_Op]] = None):
        super().__init__([], ops)
        self._source = source

    def _with_op(self, op: _Op) -> "Dataset":
        return _StreamSourceDataset(self._source, self._ops + [op])

    def _execute(self, window: int = DEFAULT_WINDOW
                 ) -> Iterator[ObjectRef]:
        from ray_tpu.data.streaming_executor import StreamingExecutor
        refs = list(self._source._execute(window))
        executor = StreamingExecutor(self._build_operators(window))
        yield from executor.execute(refs)

    def num_blocks(self) -> int:
        return self._source.num_blocks()

    def __repr__(self):
        return f"StreamSourceDataset(source={self._source!r})"


class _UnionDataset(Dataset):
    """Streaming union: each branch executes its own plan; the merged
    stream is their concatenation.  Further transforms push down into
    every branch, so laziness survives chaining."""

    def __init__(self, parts: List[Dataset]):
        super().__init__([])
        # flatten nested unions so deep chains stay one level
        flat: List[Dataset] = []
        for p in parts:
            if isinstance(p, _UnionDataset):
                flat.extend(p._parts)
            else:
                flat.append(p)
        self._parts = flat

    def _with_op(self, op: _Op) -> "Dataset":
        if isinstance(op, _MapOp):
            # stateless per-block ops distribute over the branches
            return _UnionDataset([p._with_op(op) for p in self._parts])
        # all-to-all ops (sort/shuffle/repartition) need the *global*
        # row set, and a class-UDF actor pool must be built once over
        # the merged stream (per-branch pools would double the actors
        # and the model-load cost): feed the union's stream in as one
        # input
        return _StreamSourceDataset(self, [op])

    def _execute(self, window: int = DEFAULT_WINDOW
                 ) -> Iterator[ObjectRef]:
        for p in self._parts:
            yield from p._execute(window)

    def num_blocks(self) -> int:
        return sum(p.num_blocks() for p in self._parts)

    def __repr__(self):
        return f"UnionDataset(parts={len(self._parts)})"


@ray_tpu.remote(max_retries=3)
def _head_block(block: Block, n: int) -> Block:
    return BlockAccessor.for_block(block).take_rows(np.arange(n))


class _LimitDataset(Dataset):
    """Streaming limit: pulls upstream blocks only until ``n`` rows are
    satisfied (abandoning the executor's generator stops all further
    launches), trimming the final block remotely."""

    def __init__(self, parent: Dataset, n: int):
        super().__init__([])
        self._parent = parent
        self._n = n

    def _with_op(self, op: _Op) -> "Dataset":
        # transforms after a limit operate on the n-row prefix; keep
        # them lazy — the limit runs when the chained plan is consumed
        return _StreamSourceDataset(self, [op])

    def num_blocks(self) -> int:
        # upper bound: the prefix never spans more blocks than the
        # parent has (exact count is only known at consumption)
        return self._parent.num_blocks()

    def _execute(self, window: int = DEFAULT_WINDOW
                 ) -> Iterator[ObjectRef]:
        remaining = self._n
        if remaining <= 0:
            return
        for ref in self._parent._execute(window):
            block = ray_tpu.get(ref, timeout=600)
            rows = BlockAccessor.for_block(block).num_rows()
            if rows <= remaining:
                remaining -= rows
                yield ref
            else:
                yield _head_block.remote(ref, remaining)
                remaining = 0
            if remaining <= 0:
                return

    def __repr__(self):
        return f"LimitDataset(n={self._n})"
