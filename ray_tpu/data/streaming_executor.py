"""Streaming operator-DAG executor for Datasets.

Parity: ``python/ray/data/_internal/execution/streaming_executor.py:55``
and ``operators/actor_pool_map_operator.py`` — a chain of physical
operators, each with a bounded number of in-flight tasks (backpressure),
draining completions in one scheduling loop so downstream stages overlap
upstream ones.  Differences from the reference, on purpose: budgets are
task-count based (the shm store's LRU + spill already bounds memory), and
the loop runs in the driver thread that consumes the iterator (pull
model) instead of a dedicated scheduler thread.

Operators:
- ``MapOperator`` — one task per block over a fused chain of map stages.
- ``ActorPoolMapOperator`` — stateful UDFs (``map_batches(cls)``): a
  fixed pool of actors, least-loaded dispatch, constructed once per
  actor (reference ``ActorPoolStrategy``).
- ``AllToAllOperator`` — barrier (shuffle/repartition/sort): needs every
  upstream block before emitting.

Ordering: every operator releases outputs downstream in input order, so
the final iterator is deterministic regardless of completion order.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.object_ref import ObjectRef

DEFAULT_OP_BUDGET = 8


def _ref_size(ref: ObjectRef) -> int:
    """Committed size of a block ref (0 for inline/unknown)."""
    try:
        from ray_tpu._private.worker import global_worker
        loc = global_worker().cp.get_locations(
            [ref.binary()]).get(ref.binary())
        return int(loc.get("size", 0)) if loc else 0
    except Exception:  # noqa: BLE001 — sizing is best-effort
        return 0


class PhysicalOperator:
    """Base: bounded in-flight tasks + in-order output release.

    Two backpressure axes (reference:
    ``data/_internal/execution/backpressure_policy/`` +
    ``resource_manager.py`` per-op budgets):
    - task count: at most ``budget`` concurrent tasks;
    - memory: with ``DataContext.op_bytes_budget`` set, launches pause
      while the operator's OUTSTANDING bytes (completed-but-unreleased
      buffer + estimated in-flight outputs) exceed the cap — a fat map
      stage can't balloon the object store however fast upstream feeds
      it.  One launch is always allowed when nothing is outstanding, so
      a block bigger than the budget still makes progress.
    """

    def __init__(self, name: str, budget: int = DEFAULT_OP_BUDGET):
        from ray_tpu.data.context import DataContext
        self.name = name
        self.budget = budget
        self.bytes_budget = DataContext.get_current().op_bytes_budget
        self.inqueue: deque = deque()           # (seq, ref) from upstream
        self.inflight: Dict[bytes, Tuple[int, ObjectRef]] = {}
        self._completed: Dict[int, ObjectRef] = {}
        self._next_in = 0                        # seq assigned to inputs
        self._next_out = 0                       # next seq to release
        self.input_done = False
        self.max_observed_inflight = 0
        self._out_sizes: Dict[int, int] = {}
        self._buffered_bytes = 0
        self._avg_out_bytes = 0.0
        self._n_sized = 0
        self.max_outstanding_bytes = 0

    # -- upstream side -------------------------------------------------
    def add_input(self, ref: ObjectRef) -> None:
        self.inqueue.append((self._next_in, ref))
        self._next_in += 1

    def mark_input_done(self) -> None:
        self.input_done = True

    # -- scheduling ----------------------------------------------------
    def outstanding_bytes(self) -> int:
        return int(self._buffered_bytes
                   + len(self.inflight) * self._avg_out_bytes)

    def can_launch(self) -> bool:
        if not self.inqueue or len(self.inflight) >= self.budget:
            return False
        if self.bytes_budget is not None and \
                (self.inflight or self._completed):
            if self._n_sized == 0:
                # no output-size estimate yet: probe with ONE task
                # instead of blind-launching the whole task budget
                return False
            if self.outstanding_bytes() >= self.bytes_budget:
                return False
        return True

    def launch_one(self) -> Optional[ObjectRef]:
        """Submit the next queued block; returns the task ref to track."""
        seq, ref = self.inqueue.popleft()
        out = self._submit(ref)
        self.inflight[out.binary()] = (seq, out)
        self.max_observed_inflight = max(self.max_observed_inflight,
                                         len(self.inflight))
        return out

    def _submit(self, ref: ObjectRef) -> ObjectRef:
        raise NotImplementedError

    def on_done(self, ref: ObjectRef) -> None:
        seq, out = self.inflight.pop(ref.binary())
        self._completed[seq] = out
        if self.bytes_budget is not None:
            size = _ref_size(out)
            self._out_sizes[seq] = size
            self._buffered_bytes += size
            self._n_sized += 1
            self._avg_out_bytes += (size - self._avg_out_bytes) \
                / self._n_sized
            self.max_outstanding_bytes = max(self.max_outstanding_bytes,
                                             self.outstanding_bytes())

    def release_ready(self) -> List[ObjectRef]:
        """Outputs whose predecessors have all been released (in order)."""
        out = []
        while self._next_out in self._completed:
            out.append(self._completed.pop(self._next_out))
            self._buffered_bytes -= self._out_sizes.pop(self._next_out, 0)
            self._next_out += 1
        return out

    def finished(self) -> bool:
        return (self.input_done and not self.inqueue
                and not self.inflight and not self._completed)

    def maybe_fire(self) -> None:
        """Hook for operators with non-per-input launches (barriers,
        merge phases); called every scheduling round."""

    def expected_outputs(self, n_inputs: int) -> int:
        """Output-count propagation through the chain (pre-pass)."""
        return n_inputs

    def shutdown(self) -> None:
        pass


class MapOperator(PhysicalOperator):
    def __init__(self, fused: List[Tuple[str, Callable, Dict]],
                 budget: int = DEFAULT_OP_BUDGET):
        names = "->".join(k for k, _, _ in fused)
        super().__init__(f"Map[{names}]", budget)
        self._fused = fused

    def _submit(self, ref: ObjectRef) -> ObjectRef:
        from ray_tpu.data.dataset import _map_block
        return _map_block.remote(ref, self._fused)


# num_cpus=0: the pool size already bounds concurrency, and taking CPU
# slots would let queued upstream tasks starve the pool's actor creation
# (priority inversion the reference solves with operator resource
# reservation, streaming_executor ReservationOpResourceAllocator).
@ray_tpu.remote(num_cpus=0)
class _PoolWorker:
    """One actor of an ActorPoolMapOperator: constructs the UDF once."""

    def __init__(self, cls, args, kwargs):
        self.udf = cls(*(args or ()), **(kwargs or {}))

    def apply(self, block, batch_size, batch_format):
        from ray_tpu.data.block import (BlockAccessor, batch_to_block,
                                        concat_blocks)
        acc = BlockAccessor.for_block(block)
        out = []
        for batch in acc.iter_batches(batch_size, batch_format):
            out.append(batch_to_block(self.udf(batch)))
        return concat_blocks(out) if out else block.slice(0, 0)


class ActorPoolMapOperator(PhysicalOperator):
    """Stateful map_batches: fixed actor pool, least-loaded dispatch.

    Parity: reference ``actor_pool_map_operator.py:1`` +
    ``ActorPoolStrategy``.
    """

    def __init__(self, cls, *, pool_size: int = 2,
                 fn_constructor_args=None, fn_constructor_kwargs=None,
                 batch_size: Optional[int] = None,
                 batch_format: str = "numpy",
                 budget: Optional[int] = None):
        super().__init__(f"ActorPoolMap[{getattr(cls, '__name__', cls)}]",
                         budget or 2 * pool_size)
        self._batch_size = batch_size
        self._batch_format = batch_format
        # pool is spawned lazily on the first block: metadata peeks
        # (schema/count/take) build operators too, and shouldn't pay
        # pool_size process spawns when little or no work reaches here
        self._cls = cls
        self._ctor = (fn_constructor_args, fn_constructor_kwargs)
        self._pool_size = pool_size
        self._actors: List[Any] = []
        self._load: List[int] = []
        self._ref_actor: Dict[bytes, int] = {}

    def _ensure_pool(self) -> None:
        if not self._actors:
            args, kwargs = self._ctor
            self._actors = [_PoolWorker.remote(self._cls, args, kwargs)
                            for _ in range(self._pool_size)]
            self._load = [0] * self._pool_size

    def _submit(self, ref: ObjectRef) -> ObjectRef:
        self._ensure_pool()
        i = self._load.index(min(self._load))
        self._load[i] += 1
        out = self._actors[i].apply.remote(ref, self._batch_size,
                                           self._batch_format)
        self._ref_actor[out.binary()] = i
        return out

    def on_done(self, ref: ObjectRef) -> None:
        i = self._ref_actor.pop(ref.binary(), None)
        if i is not None:
            self._load[i] -= 1
        super().on_done(ref)

    def shutdown(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self._actors = []


class ShuffleOperator(PhysicalOperator):
    """Full random shuffle with a *streaming* split stage.

    The old path materialized every upstream block at a barrier, then
    fanned out split+merge (``dataset._all_to_all_refs``) — upstream map
    work and shuffle work never overlapped.  Here each arriving block is
    split into ``num_outputs`` random parts immediately (so splits run
    concurrently with upstream maps under the normal per-op budget);
    only the per-output merges wait for the whole input, which is the
    data dependency a full shuffle cannot avoid.  Reference analogue:
    push-based shuffle's pipelined map/reduce stages
    (``data/_internal/execution/operators`` + backpressure).
    """

    def __init__(self, seed: Optional[int] = None,
                 num_outputs: Optional[int] = None,
                 budget: int = DEFAULT_OP_BUDGET):
        super().__init__("Shuffle", budget)
        # seed=None must still SHUFFLE (a fresh random seed per run) —
        # _split_block treats seed=None as a contiguous, deterministic
        # split, which is no shuffle at all
        if seed is None:
            import os as _os
            seed = int.from_bytes(_os.urandom(4), "little")
        self.seed = seed
        self.num_outputs = num_outputs   # filled by the executor pre-pass
        self._parts: Dict[int, List[ObjectRef]] = {}  # input idx -> parts
        self._n_inputs = 0
        self._merge_mode = False
        self._pending_merges: List[int] = []
        # observability: splits that completed while upstream was still
        # producing (the overlap this operator exists to create)
        self.overlapped_splits = 0

    def expected_outputs(self, n_inputs: int) -> int:
        if self.num_outputs is None:
            self.num_outputs = max(1, n_inputs)
        return self.num_outputs

    def _submit(self, ref: ObjectRef) -> ObjectRef:
        from ray_tpu.data.dataset import _fan_out, _split_block
        k = self.num_outputs or 1
        idx = self._n_inputs
        self._n_inputs += 1
        parts = _fan_out([_split_block.options(num_returns=k).remote(
            ref, k, self.seed + idx)])[0]
        self._parts[idx] = parts
        return parts[0]     # any part: all commit when the task ends

    def on_done(self, ref: ObjectRef) -> None:
        if self._merge_mode:
            super().on_done(ref)
            return
        self.inflight.pop(ref.binary())
        if not self.input_done:
            self.overlapped_splits += 1

    def release_ready(self) -> List[ObjectRef]:
        if not self._merge_mode:
            return []       # split parts are internal, not outputs
        return super().release_ready()

    def maybe_fire(self) -> None:
        if not self._merge_mode:
            if (not self.input_done or self.inqueue or self.inflight):
                return
            self._merge_mode = True
            if self._n_inputs == 0:
                return
            self._pending_merges = list(range(self.num_outputs or 1))
        # merges launch under the same budget as everything else (one
        # burst of num_outputs tasks x num_inputs args would flood the
        # scheduler the backpressure design exists to protect)
        from ray_tpu.data.dataset import _merge_blocks
        while (self._pending_merges
               and len(self.inflight) < max(self.budget, 1)):
            j = self._pending_merges.pop(0)
            out = _merge_blocks.remote(
                *[self._parts[i][j] for i in range(self._n_inputs)])
            self.inflight[out.binary()] = (j, out)
        if not self._pending_merges:
            self._parts = {}

    def finished(self) -> bool:
        return (self.input_done and not self.inqueue and self._merge_mode
                and not getattr(self, "_pending_merges", None)
                and not self.inflight and not self._completed)


class AllToAllOperator(PhysicalOperator):
    """Barrier operator: buffers every upstream block, then fans out the
    shuffle/repartition/sort tasks in one go."""

    def __init__(self, kind: str, kwargs: Dict[str, Any]):
        super().__init__(f"AllToAll[{kind}]", budget=0)
        self.kind = kind
        self.kwargs = kwargs
        self._buffer: List[ObjectRef] = []
        self._fired = False

    def can_launch(self) -> bool:
        return False  # launches happen in maybe_fire, all at once

    def expected_outputs(self, n_inputs: int) -> int:
        if self.kind == "repartition":
            return self.kwargs["num_blocks"]
        return n_inputs

    def maybe_fire(self) -> None:
        """Once upstream is exhausted, run the all-to-all (output refs
        tracked as this op's in-flight work)."""
        while self.inqueue:
            _, ref = self.inqueue.popleft()
            self._buffer.append(ref)
        if not self.input_done or self._fired:
            return
        self._fired = True
        from ray_tpu.data.dataset import _all_to_all_refs
        outs = _all_to_all_refs(self._buffer, self.kind, self.kwargs)
        self._buffer = []
        # output seqs restart at 0: release_ready tracks *outputs*, and
        # an all-to-all's output count differs from its input count
        for k, out in enumerate(outs):
            self.inflight[out.binary()] = (k, out)

    def finished(self) -> bool:
        return (self.input_done and self._fired
                and not self.inflight and not self._completed)


class StreamingExecutor:
    """Drive an operator chain, overlapping stages with bounded budgets."""

    def __init__(self, operators: List[PhysicalOperator]):
        self.operators = operators

    def execute(self, input_refs: List[ObjectRef]) -> Iterator[ObjectRef]:
        ops = self.operators
        if not ops:
            yield from input_refs
            return
        # pre-pass: propagate expected block counts (shuffle sizes its
        # output partition count from its input count)
        n = len(input_refs)
        for op in ops:
            n = op.expected_outputs(n)
        for ref in input_refs:
            ops[0].add_input(ref)
        ops[0].mark_input_done()
        try:
            yield from self._loop()
        finally:
            for op in ops:
                op.shutdown()

    def _route(self, op_idx: int, refs: List[ObjectRef]
               ) -> List[ObjectRef]:
        """Push released outputs downstream; returns final-op outputs."""
        if op_idx + 1 < len(self.operators):
            nxt = self.operators[op_idx + 1]
            for r in refs:
                nxt.add_input(r)
            return []
        return refs

    def _loop(self) -> Iterator[ObjectRef]:
        ops = self.operators
        while True:
            # propagate input-done marks downstream
            for i, op in enumerate(ops[:-1]):
                if op.finished() and not ops[i + 1].input_done:
                    ops[i + 1].mark_input_done()
            # launch whatever the budgets allow (downstream first so a
            # full pipeline drains before it refills)
            inflight: Dict[bytes, int] = {}
            for i in reversed(range(len(ops))):
                op = ops[i]
                op.maybe_fire()
                while op.can_launch():
                    op.launch_one()
                for key in op.inflight:
                    inflight[key] = i
            # release anything already complete
            emitted = False
            for i, op in enumerate(ops):
                ready = op.release_ready()
                if ready:
                    for out in self._route(i, ready):
                        emitted = True
                        yield out
            if emitted:
                continue
            if all(op.finished() for op in ops):
                return
            if not inflight:
                continue  # barrier transition: loop to propagate marks
            refs = [pair[1] for op in ops for pair in op.inflight.values()]
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=60,
                                    fetch_local=False)
            for r in ready:
                ops[inflight[r.binary()]].on_done(r)
