"""Additional datasource connectors.

Parity targets under ``python/ray/data``: ``read_webdataset``
(datasource/webdataset_datasource.py), ``read_sql``
(datasource/sql_datasource.py), ``from_torch`` / ``from_huggingface``
(read_api.py), and the matching writers.  Connectors needing client
libraries absent from the TPU image (BigQuery, Mongo, Databricks, …)
raise a clear ImportError at call time instead of shipping dead code —
the pattern to add one is any function below.
"""

from __future__ import annotations

import io
import json
import os
import tarfile
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import batch_to_block
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.read_api import _expand_paths


# ------------------------------------------------------------ webdataset
def _decode_wds_sample(sample: Dict[str, bytes]) -> Dict[str, Any]:
    """Decode one webdataset sample by extension (subset of the
    reference's auto-decoders: json/txt/cls decode, images stay bytes)."""
    out: Dict[str, Any] = {}
    for key, data in sample.items():
        ext = key.rsplit(".", 1)[-1]
        if ext == "json":
            out[key] = json.loads(data)
        elif ext in ("txt", "text"):
            out[key] = data.decode()
        elif ext in ("cls", "index"):
            out[key] = int(data)
        elif ext == "npy":
            out[key] = np.load(io.BytesIO(data))
        else:
            out[key] = data          # images etc: raw bytes
    return out


@ray_tpu.remote(max_retries=3)
def _read_wds_shard(path: str) -> pa.Table:
    """One tar shard -> one block.  Samples are files sharing a basename
    prefix: ``0001.jpg`` + ``0001.cls`` is one sample with two fields."""
    samples: Dict[str, Dict[str, bytes]] = {}
    with tarfile.open(path) as tf:
        for member in tf:
            if not member.isfile():
                continue
            base, _, ext = member.name.partition(".")
            fh = tf.extractfile(member)
            if fh is None:
                continue
            samples.setdefault(base, {"__key__": base.encode()})[ext] = \
                fh.read()
    rows = []
    for base in sorted(samples):
        raw = samples[base]
        key = raw.pop("__key__").decode()
        row = _decode_wds_sample(raw)
        row["__key__"] = key
        rows.append(row)
    return pa.Table.from_pylist(rows)


def read_webdataset(paths) -> Dataset:
    """Read webdataset tar shards, one block per shard."""
    return Dataset([_read_wds_shard.remote(p)
                    for p in _expand_paths(paths)])


def write_webdataset(ds: Dataset, path: str) -> None:
    """Write each block as one tar shard; bytes columns become files
    named ``<row_key>.<column>``."""
    os.makedirs(path, exist_ok=True)
    for i, ref in enumerate(ds._execute()):
        block = ray_tpu.get(ref, timeout=600)
        shard = os.path.join(path, f"shard-{i:05d}.tar")
        with tarfile.open(shard, "w") as tf:
            for r, row in enumerate(block.to_pylist()):
                key = str(row.pop("__key__", f"{i:05d}{r:06d}"))
                for col, value in row.items():
                    if isinstance(value, bytes):
                        data = value
                    elif isinstance(value, str):
                        data = value.encode()
                    else:
                        data = json.dumps(value).encode()
                        col = f"{col}.json" if "." not in col else col
                    info = tarfile.TarInfo(f"{key}.{col}")
                    info.size = len(data)
                    tf.addfile(info, io.BytesIO(data))


# ------------------------------------------------------------------ sql
def read_sql(sql: str, connection_factory: Callable[[], Any], *,
             override_num_blocks: int = 1) -> Dataset:
    """Read a DB-API 2.0 query result (reference:
    ``ray.data.read_sql``).  ``connection_factory`` must be picklable
    (e.g. ``lambda: sqlite3.connect(path)``); the query runs inside a
    task on the cluster."""

    @ray_tpu.remote(max_retries=3)
    def _query() -> pa.Table:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        return pa.Table.from_pylist(
            [dict(zip(cols, r)) for r in rows])

    table_ref = _query.remote()
    if override_num_blocks <= 1:
        return Dataset([table_ref])
    table = ray_tpu.get(table_ref, timeout=600)
    n = max(1, table.num_rows // override_num_blocks)
    refs = [ray_tpu.put(table.slice(off, n))
            for off in range(0, table.num_rows, n)]
    return Dataset(refs)


# ------------------------------------------------- framework ingestion
def from_torch(torch_dataset) -> Dataset:
    """Materialize a (map-style) ``torch.utils.data.Dataset``
    (reference: ``ray.data.from_torch``)."""
    def to_plain(v: Any) -> Any:
        if hasattr(v, "numpy"):                  # torch.Tensor
            v = v.numpy()
        if isinstance(v, np.ndarray):
            return v.tolist()
        return v

    rows = []
    for i in range(len(torch_dataset)):
        item = torch_dataset[i]
        if isinstance(item, dict):
            rows.append({k: to_plain(v) for k, v in item.items()})
        elif isinstance(item, (tuple, list)):
            rows.append({f"item_{j}": to_plain(v)
                         for j, v in enumerate(item)})
        else:
            rows.append({"item": to_plain(item)})
    if not rows:
        return Dataset([ray_tpu.put(pa.table({"item": pa.array([])}))])
    return Dataset([ray_tpu.put(pa.Table.from_pylist(rows))])


def from_huggingface(hf_dataset) -> Dataset:
    """Zero-copy a 🤗 ``datasets.Dataset`` via its arrow table
    (reference: ``ray.data.from_huggingface``)."""
    try:
        table = hf_dataset.data.table
    except AttributeError as e:
        raise TypeError(
            "from_huggingface expects a `datasets.Dataset` (install the "
            "`datasets` package in the image)") from e
    return Dataset([ray_tpu.put(table.combine_chunks())])


# ---------------------------------------------------------------- write
def write_json(ds: Dataset, path: str) -> None:
    """One JSON-lines file per block (reference: ``Dataset.write_json``)."""
    os.makedirs(path, exist_ok=True)
    for i, ref in enumerate(ds._execute()):
        block = ray_tpu.get(ref, timeout=600)
        with open(os.path.join(path, f"part-{i:05d}.jsonl"), "w") as f:
            for row in block.to_pylist():
                f.write(json.dumps(_json_row(row)) + "\n")


def _json_row(row: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in row.items():
        if isinstance(v, np.ndarray):
            out[k] = v.tolist()
        elif isinstance(v, bytes):
            out[k] = v.hex()
        elif isinstance(v, np.generic):
            out[k] = v.item()
        else:
            out[k] = v
    return out


def write_numpy(ds: Dataset, path: str, column: str) -> None:
    """One ``.npy`` per block from ``column``
    (reference: ``Dataset.write_numpy``)."""
    os.makedirs(path, exist_ok=True)
    for i, ref in enumerate(ds._execute()):
        block = ray_tpu.get(ref, timeout=600)
        col = block.column(column).to_numpy(zero_copy_only=False)
        np.save(os.path.join(path, f"part-{i:05d}.npy"), np.stack(col)
                if col.dtype == object else col)
