"""The streaming training data plane: a deterministic, preemption-proof
input pipeline.

Shaped after the reference's streaming executor + backpressure policies
(``data/_internal/execution/streaming_executor.py``,
``backpressure_policy/``) but specialized to the one workload that
matters here: feeding ``[B, S]`` token batches to a train host at step
rate, overlapping all host work (shard reads, tokenized-document
packing, host->device transfer) with the device step — the r08 prefetch
idiom applied on the host side, per arXiv:2011.03641's
concurrency-limits argument.

The load-bearing constraint is **determinism under preemption**: every
batch is a pure function of ``(seed, cursor)`` — the seed is part of
the stream's identity (carried in the cursor and validated on resume;
today the document schedule is deterministic round-robin, so the seed
is the hook where a future shuffle stage derives its permutations, not
a source of randomness yet).

- :class:`~ray_tpu.data.source.DocumentSource` reads are pure, so a
  shard-reader death is recovered by restarting the reader and
  re-issuing the fetch verbatim — exactly-once sample accounting with
  no acknowledgement protocol.
- The :class:`StreamCursor` captures per-shard offsets, the packer
  residue (closed-but-unemitted rows + the partial row) and, by
  construction, the in-flight queue state: the cursor paired with a
  delivered batch describes the stream *after* that batch, so batches
  still sitting in the prefetch queue at a kill are simply regenerated
  — bit-for-bit — on resume.  Serialized as a fixed-capacity uint8
  array it rides :class:`~ray_tpu.resilience.checkpoint.
  TrainCheckpointer` ``extras`` through both the orbax and npz paths.

Deterministic fault sites (``RAY_TPU_FAULTS``, ``util/chaos.py``):
``data.read`` (a shard fetch dies — the plane restarts the reader and
re-issues; a ``data.read@N..M:delay=S`` entry instead *slows* the
fetch, the gray failure the hedged read mitigates), ``data.pack`` (a
batch assembly dies before mutating packer state — retried),
``data.stall`` (a shard read sleeps — prefer ``:delay=S``; the bare
form sleeps the deprecated ``RAY_TPU_DATA_STALL_S`` alias).

**Hedged reads** (r19): with ``RAY_TPU_DATA_HEDGE`` > 0, a shard read
that outlives the hedge budget is re-issued to a standby reader and
the first response wins.  Exactly-once needs no protocol: sources are
pure (both responses are byte-identical) and only cursor advancement
consumes a document — the loser's response is simply discarded.
"""

from __future__ import annotations

import dataclasses
import json
import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

from ray_tpu.data.config import data_config
from ray_tpu.data.packer import PackedBatch, SamplePacker
from ray_tpu.data.source import DocumentSource
from ray_tpu.util import chaos

# fetch granularity: documents per reader round-trip (amortizes actor
# call overhead; determinism is unaffected — consumption order is the
# cursor's round-robin schedule, not fetch-completion order)
READ_CHUNK = 16

# default serialized-cursor capacity (bytes).  Fixed so checkpoint
# restore validation (shape/dtype leaf checks) accepts any snapshot of
# the same stream; the JSON payload length rides in a 4-byte prefix.
CURSOR_CAPACITY = 32768


class DataPlaneError(RuntimeError):
    """A shard read kept failing past ``RAY_TPU_DATA_RETRIES`` (or the
    pack stage did) — the input pipeline is down, loudly, instead of
    spinning or silently skipping samples."""


# ------------------------------------------------------------- cursor
@dataclasses.dataclass
class StreamCursor:
    """The exact stream position: everything needed to regenerate the
    next batch (and all batches after it) bit-identically.

    ``shard_offsets[s]`` is the next unread document index of shard
    ``s``; ``rotation`` the next shard the round-robin schedule draws
    from; ``packer`` the residue (see
    :meth:`~ray_tpu.data.packer.SamplePacker.state_dict`).  The
    geometry fields (``num_shards``/``batch_size``/``seq_len``/
    ``pack``) are validated on resume — restoring a cursor into a
    different stream shape must fail loudly, not replay garbage.
    """
    seed: int
    num_shards: int
    batch_size: int
    seq_len: int
    pack: bool
    shard_offsets: List[int]
    rotation: int = 0
    epoch: int = 0
    batches: int = 0
    docs: int = 0
    packer: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def copy(self) -> "StreamCursor":
        return StreamCursor(
            seed=self.seed, num_shards=self.num_shards,
            batch_size=self.batch_size, seq_len=self.seq_len,
            pack=self.pack, shard_offsets=list(self.shard_offsets),
            rotation=self.rotation, epoch=self.epoch,
            batches=self.batches, docs=self.docs,
            packer=json.loads(json.dumps(self.packer)))

    # ------------------------------------------------- serialization
    def to_array(self, capacity: int = CURSOR_CAPACITY) -> np.ndarray:
        """Fixed-capacity uint8 image: 4-byte LE length + JSON payload,
        zero-padded — constant shape/dtype so checkpoint restore
        validation accepts every snapshot of one stream."""
        payload = json.dumps(dataclasses.asdict(self),
                             separators=(",", ":")).encode()
        if len(payload) + 4 > capacity:
            raise ValueError(
                f"serialized stream cursor is {len(payload)} bytes, "
                f"over the {capacity}-byte capacity — raise "
                "cursor_capacity (packer residue grows with B*S)")
        arr = np.zeros(capacity, np.uint8)
        arr[:4] = np.frombuffer(
            len(payload).to_bytes(4, "little"), np.uint8)
        arr[4:4 + len(payload)] = np.frombuffer(payload, np.uint8)
        return arr

    @staticmethod
    def from_array(arr: np.ndarray) -> "StreamCursor":
        raw = np.asarray(arr, np.uint8).tobytes()
        n = int.from_bytes(raw[:4], "little")
        if not 0 < n <= len(raw) - 4:
            raise ValueError(f"corrupt stream-cursor array (payload "
                             f"length {n} of {len(raw)} bytes)")
        state = json.loads(raw[4:4 + n].decode())
        return StreamCursor(**state)


# ------------------------------------------------------------- readers
def _read_docs(source: DocumentSource, shard: int, start: int,
               count: int):
    """The one read path both reader modes share — chaos sites fire
    here so in-process and actor readers exercise identical faults."""
    chaos.maybe_fail("data.read")
    if chaos.should_fire("data.stall"):
        time.sleep(data_config().stall_s)
    return source.read(shard, start, count)


class _InProcessReader:
    """readers=0: shard reads on the producer thread (host-sim)."""

    def __init__(self, source: DocumentSource):
        self._source = source

    def read(self, shard: int, start: int, count: int):
        return _read_docs(self._source, shard, start, count)

    def restart(self) -> None:
        pass


def _reader_actor_cls():
    # num_cpus=0: reader concurrency is bounded by the schedule, and
    # taking CPU slots would let queued work starve actor creation
    # (the streaming_executor _PoolWorker precedent)
    global _READER_ACTOR
    if _READER_ACTOR is None:
        import ray_tpu

        @ray_tpu.remote(num_cpus=0)
        class _ReaderActor:
            """One stateless shard reader: fetches are pure functions
            of the source, so a restarted actor re-serves any fetch
            verbatim."""

            def __init__(self, source):
                self.source = source

            def read(self, shard, start, count):
                from ray_tpu.data.stream import _read_docs
                return _read_docs(self.source, shard, start, count)

        _READER_ACTOR = _ReaderActor
    return _READER_ACTOR


_READER_ACTOR = None


class _ActorReader:
    """One restartable shard-reader actor.  The actor holds no stream
    state (the source is pure), so restart = recreate: the re-issued
    fetch returns the identical documents."""

    def __init__(self, source: DocumentSource):
        self._source = source
        self._actor = None

    def _ensure(self):
        if self._actor is None:
            self._actor = _reader_actor_cls().remote(self._source)
        return self._actor

    def read(self, shard: int, start: int, count: int):
        import ray_tpu
        return ray_tpu.get(
            self._ensure().read.remote(shard, start, count),
            timeout=data_config().read_timeout_s)

    def restart(self) -> None:
        import ray_tpu
        if self._actor is not None:
            try:
                ray_tpu.kill(self._actor)
            except Exception:  # noqa: BLE001 — it may already be dead
                pass
        self._actor = None


class _DocSchedule:
    """The deterministic document iterator the cursor describes:
    round-robin across shards by ``cursor.rotation``/``shard_offsets``,
    epoch wrap when every shard drains, chunked fetches through
    restartable readers with a bounded retry budget.

    Shared by the training loader and the RL prompt dataset
    (:class:`ray_tpu.rl.rollout.PromptDataset`) so both replay
    identically from a cursor."""

    def __init__(self, source: DocumentSource, cursor: StreamCursor, *,
                 readers: int = 0, retries: int = 3,
                 hedge_s: Optional[float] = None, telemetry=None):
        self.source = source
        self.cursor = cursor
        self.retries = int(retries)
        self.hedge_s = data_config().hedge_s if hedge_s is None \
            else float(hedge_s)
        self.telemetry = telemetry
        self.reader_restarts = 0
        # read_hedges counts hedges ISSUED; telemetry's
        # record_read_hedge fires per hedge RESOLVED by a returning
        # leg — an attempt where both legs fail is counted here but
        # not there (it surfaces through the retry/restart counters)
        self.read_hedges = 0
        self.read_hedges_won = 0
        if readers > 0:
            self._readers = [_ActorReader(source) for _ in range(readers)]
        else:
            self._readers = [_InProcessReader(source)]
        self._standby: Optional[_InProcessReader] = None
        self._buf: Dict[int, List] = {}      # shard -> [(start, docs)]
        self._buf_start: Dict[int, int] = {}

    def _standby_reader(self, shard: int):
        """The reader a hedge re-issues to: the next reader replica
        when there is one, else a dedicated in-process reader over the
        same pure source (identical bytes either way)."""
        if len(self._readers) > 1:
            return self._readers[(shard + 1) % len(self._readers)]
        if self._standby is None:
            self._standby = _InProcessReader(self.source)
        return self._standby

    @staticmethod
    def _spawn_read(reader, shard: int, start: int, count: int,
                    wake: threading.Event) -> dict:
        """Run one read leg on a *daemon* thread (a leg parked in a
        genuinely hung read must neither block interpreter exit nor
        need a pool teardown the loader would have to own).  The box
        gains ``docs`` or ``err``, written before ``wake`` fires."""
        box: dict = {}

        def run():
            try:
                box["docs"] = reader.read(shard, start, count)
            except BaseException as e:  # noqa: BLE001 — leg lost
                box["err"] = e
            finally:
                wake.set()

        threading.Thread(target=run, name="data-read",
                         daemon=True).start()
        return box

    def _hedged_read(self, reader, shard: int, start: int, count: int):
        """One read attempt with a tail hedge: the primary runs on a
        daemon thread; past ``hedge_s`` with no response, a standby
        read races it and the first *successful* response wins.  The
        loser's (identical, by purity) response is discarded; a leg
        that errors just cedes the race, and only both legs failing
        fails the attempt."""
        wake = threading.Event()
        pbox = self._spawn_read(reader, shard, start, count, wake)
        wake.wait(self.hedge_s)
        if "docs" in pbox:
            return pbox["docs"]
        if "err" in pbox:
            raise pbox["err"]         # fast failure: the retry loop's
        self.read_hedges += 1
        sbox = self._spawn_read(self._standby_reader(shard), shard,
                                start, count, wake)
        while True:
            wake.clear()
            # primary checked first on a same-wake tie: "won" must
            # mean the standby genuinely beat it (box writes happen
            # before the wake, so a set flag means a decided leg)
            for box, is_standby in ((pbox, False), (sbox, True)):
                if "docs" in box:
                    if is_standby:
                        self.read_hedges_won += 1
                    if self.telemetry is not None:
                        self.telemetry.record_read_hedge(won=is_standby)
                    return box["docs"]
            if "err" in pbox and "err" in sbox:
                raise sbox["err"]
            wake.wait()

    def _fetch(self, shard: int, start: int, count: int):
        reader = self._readers[shard % len(self._readers)]
        for attempt in range(self.retries + 1):
            try:
                if self.hedge_s > 0:
                    return self._hedged_read(reader, shard, start,
                                             count)
                return reader.read(shard, start, count)
            except Exception as e:  # noqa: BLE001 — restart + re-issue
                if attempt >= self.retries:
                    raise DataPlaneError(
                        f"shard {shard} read at offset {start} failed "
                        f"{attempt + 1}x (retry budget "
                        f"{self.retries}): {e!r}") from e
                reader.restart()
                self.reader_restarts += 1
                if self.telemetry is not None:
                    self.telemetry.record_reader_restart()

    def _doc_at(self, shard: int, offset: int):
        docs = self._buf.get(shard)
        start = self._buf_start.get(shard, -1)
        if docs is None or not (start <= offset < start + len(docs)):
            docs = self._fetch(shard, offset, READ_CHUNK)
            self._buf[shard] = docs
            self._buf_start[shard] = offset
            start = offset
        return docs[offset - start]

    def next_doc(self, *, epochs: Optional[int] = None):
        """The next ``(doc_id, tokens)`` of the schedule, or None when
        a finite stream (``epochs``) is drained."""
        c = self.cursor
        for _wrap in range(2):
            n = c.num_shards
            for _ in range(n):
                s = c.rotation
                c.rotation = (c.rotation + 1) % n
                if c.shard_offsets[s] >= self.source.docs_in_shard(s):
                    continue
                doc = self._doc_at(s, c.shard_offsets[s])
                c.shard_offsets[s] += 1
                c.docs += 1
                return doc
            # every shard drained: epoch boundary
            c.epoch += 1
            if epochs is not None and c.epoch >= epochs:
                return None
            c.shard_offsets = [0] * n
            c.rotation = 0
            self._buf.clear()
            self._buf_start.clear()
        raise DataPlaneError("document source is empty (no shard has "
                             "any documents)")


# -------------------------------------------------------------- loader
@dataclasses.dataclass
class StreamBatch:
    """One delivered batch + the cursor that regenerates its
    successors (what the train loop puts in checkpoint extras)."""
    batch: Dict[str, Any]          # tokens/targets/segment_ids/positions
    cursor: StreamCursor           # stream state AFTER this batch
    spans: List                    # (row, col, doc_id, n) audit trail
    packed_tokens: int
    cursor_capacity: int = CURSOR_CAPACITY
    _cursor_array: Optional[np.ndarray] = dataclasses.field(
        default=None, repr=False)

    @property
    def cursor_array(self) -> np.ndarray:
        """Fixed-capacity ckpt serialization — built lazily so
        batches that never reach a checkpointer (``RAY_TPU_CKPT_EVERY``
        off or off-cadence) pay no JSON encode or 32 KB buffer."""
        if self._cursor_array is None:
            self._cursor_array = self.cursor.to_array(
                self.cursor_capacity)
        return self._cursor_array


_DONE = object()


class StreamingLoader:
    """Bounded-prefetch, double-buffered, cursor-exact batch stream.

    A producer thread runs the deterministic assembler (schedule ->
    packer -> ``[B, S]`` arrays) and fills a ``prefetch``-bounded queue
    — backpressure against a slow trainer by construction.  The
    consumer (:meth:`next`) keeps one batch staged on device and
    dispatches the next ``device_put`` before returning, so host->
    device transfer hides under the step (``jax.device_put`` is
    async-dispatched).

    Every delivered :class:`StreamBatch` carries the cursor of the
    stream *after* it; resuming with ``cursor=`` replays the identical
    continuation — batches that were sitting in the prefetch queue at
    a kill are regenerated, not lost (and never duplicated, because
    the checkpointed cursor only ever advances at delivery).
    """

    def __init__(self, source: DocumentSource, *, batch_size: int,
                 seq_len: int, seed: int = 0,
                 cursor: Union[None, StreamCursor, np.ndarray] = None,
                 epochs: Optional[int] = None,
                 pack: Optional[bool] = None,
                 prefetch: Optional[int] = None,
                 readers: Optional[int] = None,
                 retries: Optional[int] = None,
                 hedge_s: Optional[float] = None,
                 device_put: bool = True,
                 sharding=None,
                 cursor_capacity: int = CURSOR_CAPACITY,
                 telemetry=None):
        dcfg = data_config()
        self.source = source
        self.epochs = epochs
        self.pack = dcfg.pack if pack is None else bool(pack)
        self.prefetch = dcfg.prefetch if prefetch is None else \
            max(1, int(prefetch))
        self.retries = dcfg.retries if retries is None else int(retries)
        readers = dcfg.readers if readers is None else int(readers)
        self.device_put = device_put
        self.sharding = sharding
        self.cursor_capacity = int(cursor_capacity)
        from ray_tpu.telemetry.data import DataTelemetry
        self.telemetry = telemetry if telemetry is not None \
            else DataTelemetry()
        if cursor is None:
            cursor = StreamCursor(
                seed=int(seed), num_shards=source.num_shards,
                batch_size=int(batch_size), seq_len=int(seq_len),
                pack=self.pack,
                shard_offsets=[0] * source.num_shards)
        elif not isinstance(cursor, StreamCursor):
            cursor = StreamCursor.from_array(cursor)
        want = (source.num_shards, int(batch_size), int(seq_len),
                self.pack, int(seed))
        got = (cursor.num_shards, cursor.batch_size, cursor.seq_len,
               cursor.pack, cursor.seed)
        if want != got:
            raise ValueError(
                f"stream cursor geometry mismatch: cursor has "
                f"(shards, B, S, pack, seed)={got}, loader wants "
                f"{want} — a cursor only resumes the stream it was "
                "taken from")
        self._cursor = cursor.copy()
        self._packer = SamplePacker(batch_size, seq_len, pack=self.pack)
        if cursor.packer:
            self._packer.load_state(cursor.packer)
        self._schedule = _DocSchedule(
            source, self._cursor, readers=readers, retries=self.retries,
            hedge_s=hedge_s, telemetry=self.telemetry)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        self._stop = threading.Event()
        self._staged: Optional[StreamBatch] = None
        self._pending_error: Optional[BaseException] = None
        self._primed = False
        self._drained = False
        self._thread = threading.Thread(target=self._produce,
                                        daemon=True,
                                        name="data-producer")
        self._thread.start()

    # ----------------------------------------------------- producer
    def _assemble(self) -> Optional[PackedBatch]:
        """One deterministic batch (or None at end of a finite
        stream).  The ``data.pack`` site fires before any packer
        mutation, so a retry replays the identical assembly."""
        exhausted = False
        for attempt in range(self.retries + 1):
            try:
                chaos.maybe_fail("data.pack")
                break
            except chaos.InjectedFault:
                self.telemetry.record_pack_retry()
                if attempt >= self.retries:
                    raise DataPlaneError(
                        "batch assembly failed past the retry budget "
                        f"({self.retries})")
        while not self._packer.ready:
            doc = self._schedule.next_doc(epochs=self.epochs)
            if doc is None:
                exhausted = True
                self._packer.flush()
                break
            self._packer.add(*doc)
        return self._packer.pop_batch(allow_partial=exhausted)

    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                # wall covers assembly + snapshot ONLY — including the
                # block inside _put (a full queue) would collapse the
                # input-tok/s gauge to the consumer's rate under
                # backpressure, hiding which side has headroom
                t0 = time.monotonic()
                pb = self._assemble()
                if pb is None:
                    self._put(_DONE)
                    return
                c = self._cursor
                c.batches += 1
                c.packer = self._packer.state_dict()
                snap = c.copy()
                sb = StreamBatch(
                    batch=pb.as_train_batch(with_segments=self.pack),
                    cursor=snap,
                    spans=pb.spans, packed_tokens=pb.packed_tokens,
                    cursor_capacity=self.cursor_capacity)
                wall = time.monotonic() - t0
                self._put(sb)
                self.telemetry.record_batch(
                    pb.packed_tokens, wall,
                    queue_depth=self._q.qsize())
        except BaseException as e:  # noqa: BLE001 — surface on next()
            self._put(e)

    def _put(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    # ----------------------------------------------------- consumer
    def _pop(self) -> Optional[StreamBatch]:
        if self._drained:
            return None
        t0 = time.monotonic()
        item = self._q.get()
        self.telemetry.record_stall(time.monotonic() - t0)
        if item is _DONE:
            self._drained = True
            return None
        if isinstance(item, BaseException):
            self._drained = True
            if isinstance(item, DataPlaneError):
                raise item
            raise DataPlaneError(
                f"data producer died: {item!r}") from item
        if self.device_put and item is not None:
            import jax
            item.batch = (jax.device_put(item.batch, self.sharding)
                          if self.sharding is not None
                          else jax.device_put(item.batch))
        return item

    def next(self) -> StreamBatch:
        """The next batch, device-resident, with its cursor.  The
        successor's transfer is dispatched before returning (double
        buffering) so it copies while the caller steps.

        A producer error encountered while staging the successor is
        held back until the already-produced staged batch has been
        delivered — errors never cost a good batch or reorder
        delivery."""
        if not self._primed:
            self._staged = self._pop()
            self._primed = True
        if self._pending_error is not None:
            err, self._pending_error = self._pending_error, None
            raise err
        out = self._staged
        if out is None:
            raise StopIteration
        try:
            self._staged = self._pop()
        except DataPlaneError as e:
            self._staged = None
            self._pending_error = e
        return out

    def set_sharding(self, sharding) -> None:
        """Re-point delivery at a new mesh (the elastic shrink/expand
        path): subsequent batches ``device_put`` onto ``sharding``,
        and the already-staged double-buffered batch is re-placed so
        the very next :meth:`next` also lands on the new topology —
        the cursor, packer residue and prefetch queue are untouched,
        which is what keeps the consumed document sequence identical
        across topology changes."""
        self.sharding = sharding
        if self.device_put and self._primed and \
                self._staged is not None:
            import jax
            self._staged.batch = jax.device_put(
                jax.tree.map(lambda x: np.asarray(x),
                             self._staged.batch), sharding)

    def __iter__(self) -> Iterator[StreamBatch]:
        while True:
            try:
                yield self.next()
            except StopIteration:
                return

    def close(self) -> None:
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10)

    def __enter__(self) -> "StreamingLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
