"""Datasource read/create APIs.

Parity: ``python/ray/data/read_api.py`` — range/from_items/from_numpy/
from_pandas/from_arrow + file readers (parquet, csv, json, text, binary,
images) on pyarrow.  Reads are lazy-ish: file reads happen in tasks at
execution time.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Dict, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import batch_to_block
from ray_tpu.data.dataset import Dataset


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(
                f for f in _glob.glob(os.path.join(p, "**", "*"),
                                      recursive=True)
                if os.path.isfile(f)))
        elif any(c in p for c in "*?["):
            files.extend(sorted(_glob.glob(p)))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no files matched {paths}")
    return files


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:
    blocks = override_num_blocks or min(max(1, n // 1000), 64)
    bounds = np.linspace(0, n, blocks + 1).astype(int)
    refs = []
    for i in np.arange(blocks):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        refs.append(ray_tpu.put(
            pa.table({"id": pa.array(np.arange(lo, hi))})))
    return Dataset(refs)


def from_items(items: List[Any], *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    if items and not isinstance(items[0], dict):
        items = [{"item": x} for x in items]
    blocks = override_num_blocks or min(max(1, len(items) // 1000), 16)
    parts = np.array_split(np.arange(len(items)), blocks)
    refs = [ray_tpu.put(pa.Table.from_pylist(
        [items[i] for i in part])) for part in parts if len(part)]
    if not refs:
        refs = [ray_tpu.put(pa.table({"item": pa.array([])}))]
    return Dataset(refs)


def from_numpy(arr: np.ndarray, column: str = "data") -> Dataset:
    return Dataset([ray_tpu.put(batch_to_block({column: arr}))])


def from_arrow(table: pa.Table) -> Dataset:
    return Dataset([ray_tpu.put(table)])


def from_pandas(df) -> Dataset:
    return Dataset([ray_tpu.put(
        pa.Table.from_pandas(df, preserve_index=False))])


# ------------------------------------------------------------ file readers
@ray_tpu.remote(max_retries=3)
def _read_file_task(path: str, fmt: str, kwargs: Dict[str, Any]):
    if fmt == "parquet":
        import pyarrow.parquet as pq
        return pq.read_table(path, **kwargs)
    if fmt == "csv":
        import pyarrow.csv as pacsv
        return pacsv.read_csv(path, **kwargs)
    if fmt == "json":
        import pyarrow.json as pajson
        return pajson.read_json(path, **kwargs)
    if fmt == "text":
        with open(path) as f:
            lines = [line.rstrip("\n") for line in f]
        return pa.table({"text": pa.array(lines)})
    if fmt == "binary":
        with open(path, "rb") as f:
            data = f.read()
        return pa.table({"bytes": pa.array([data], pa.binary()),
                         "path": pa.array([path])})
    if fmt == "numpy":
        arr = np.load(path)
        return batch_to_block({"data": arr})
    if fmt == "image":
        from PIL import Image
        img = np.asarray(Image.open(path))
        return batch_to_block({"image": img[None, ...]})
    raise ValueError(f"unknown format {fmt}")


def _read_files(paths, fmt: str, **kwargs) -> Dataset:
    files = _expand_paths(paths)
    refs = [_read_file_task.remote(f, fmt, kwargs) for f in files]
    return Dataset(refs)


def read_parquet(paths, **kwargs) -> Dataset:
    return _read_files(paths, "parquet", **kwargs)


def read_csv(paths, **kwargs) -> Dataset:
    return _read_files(paths, "csv", **kwargs)


def read_json(paths, **kwargs) -> Dataset:
    return _read_files(paths, "json", **kwargs)


def read_text(paths, **kwargs) -> Dataset:
    return _read_files(paths, "text", **kwargs)


def read_binary_files(paths, **kwargs) -> Dataset:
    return _read_files(paths, "binary", **kwargs)


def read_numpy(paths, **kwargs) -> Dataset:
    return _read_files(paths, "numpy", **kwargs)


def read_images(paths, **kwargs) -> Dataset:
    return _read_files(paths, "image", **kwargs)
