"""Per-consumer stream iterators (``Dataset.streaming_split``).

Parity: reference ``Dataset.streaming_split`` +
``data/_internal/execution/operators/output_splitter.py`` — one
execution of the dataset feeds N consumers *disjoint* block streams, so
N Train workers ingest one epoch cooperatively without materializing or
duplicating it.  A coordinator actor owns the single streaming
execution; iterators (cheap, serializable — they travel to the train
workers) pull blocks from it.

Dispatch: by default first-come-first-served (a fast consumer takes
more blocks — the reference's default load-balancing behavior);
``equal=True`` hands blocks out in complete rounds and row-splits the
final partial round so every consumer sees the same number of blocks
(±1 row), which gang-stepping SPMD workers need to stay in lock step.

Epochs: each fresh iteration of a ``DataIterator`` is one epoch.  The
coordinator starts the next epoch's execution once every consumer has
either drained or *abandoned* the previous one (requesting epoch k+1
counts as abandoning k — a ``islice``-style partial epoch does not wedge
the stream).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Iterator, List, Optional

import ray_tpu


@ray_tpu.remote(num_cpus=0)
class _SplitCoordinator:
    def __init__(self, ds_blob: bytes, n: int, equal: bool):
        import cloudpickle
        self._ds = cloudpickle.loads(ds_blob)
        self._n = n
        self._equal = equal
        self._epoch = 0
        self._reset()

    def _reset(self):
        self._gen = None
        self._queues: List[deque] = [deque() for _ in range(self._n)]
        self._done = False
        self._round: List[Any] = []     # equal mode: blocks of one round
        # consumers that finished or abandoned the current epoch
        self._moved_on: set = set()

    def _advance_round(self, final: bool) -> None:
        """equal mode: release buffered blocks once a full round of n is
        collected; at stream end, row-split the partial round n ways so
        consumers stay block-count equal."""
        if len(self._round) == self._n:
            for i, ref in enumerate(self._round):
                self._queues[i].append(ref)
            self._round = []
        elif final and self._round:
            from ray_tpu.data.dataset import _fan_out, _split_block
            for ref in self._round:
                parts = _fan_out([_split_block.options(
                    num_returns=self._n).remote(ref, self._n, None)])[0]
                for i, p in enumerate(parts):
                    self._queues[i].append(p)
            self._round = []

    def next_block_ref(self, split: int, epoch: int = 0):
        """Pull the next block for consumer ``split`` within ``epoch``.

        Returns ``("ref", ref)``, ``("end",)`` when the epoch's stream
        is exhausted for this consumer, or ``("wait",)`` while other
        consumers are still on the previous epoch.
        """
        if epoch < self._epoch:
            return ("end",)     # a stream the caller already left behind
        if epoch > self._epoch:
            self._moved_on.add(split)
            if len(self._moved_on) == self._n:
                # everyone is past the old epoch: restart the stream
                if self._gen is not None:
                    self._gen.close()
                self._epoch = epoch
                self._reset()
            else:
                return ("wait",)
        if self._gen is None:
            self._gen = self._ds._execute()
        q = self._queues[split]
        if q:
            return ("ref", q.popleft())
        while True:
            if self._done:
                self._moved_on.add(split)
                return ("end",)
            try:
                ref = next(self._gen)
            except StopIteration:
                self._done = True
                if self._equal:
                    self._advance_round(final=True)
                    if q:
                        return ("ref", q.popleft())
                self._moved_on.add(split)
                return ("end",)
            if not self._equal:
                return ("ref", ref)  # greedy: the asker takes the block
            self._round.append(ref)
            self._advance_round(final=False)
            if q:
                return ("ref", q.popleft())

    def stats(self):
        return {"done": self._done, "epoch": self._epoch,
                "queued": [len(q) for q in self._queues]}


class _CoordinatorOwner:
    """Driver-side owner: kills the coordinator actor when the last
    driver-held iterator is GC'd (worker-side copies never own it).

    Also pins the source dataset: its block ObjectRefs travel to the
    coordinator inside an opaque pickle blob that dependency pinning
    cannot see, so this strong reference is what keeps them alive until
    the coordinator has unpickled (and thereby re-registered) them."""

    def __init__(self, coordinator, dataset=None):
        self.coordinator = coordinator
        self.dataset = dataset

    def __del__(self):
        try:
            ray_tpu.kill(self.coordinator)
        except Exception:  # noqa: BLE001 — shutdown/interp teardown
            pass


class DataIterator:
    """One consumer's view of a streaming split (serializable).

    Each fresh iteration (``iter_block_refs``/``iter_batches``/...)
    consumes one epoch; the coordinator restarts the stream once every
    consumer has drained or abandoned the previous epoch."""

    def __init__(self, coordinator: Any, split: int, epoch: int = 0):
        self._coord = coordinator
        self._split = split
        self._epoch = epoch
        self._owner: Optional[_CoordinatorOwner] = None

    def iter_block_refs(self) -> Iterator[Any]:
        import time
        epoch = self._epoch
        self._epoch += 1
        while True:
            out = ray_tpu.get(
                self._coord.next_block_ref.remote(self._split, epoch),
                timeout=600)
            if out[0] == "wait":
                time.sleep(0.05)
                continue
            if out[0] == "end":
                return
            yield out[1]

    def iter_blocks(self) -> Iterator[Any]:
        for ref in self.iter_block_refs():
            yield ray_tpu.get(ref, timeout=600)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     prefetch_blocks: int = 0) -> Iterator[Any]:
        from ray_tpu.data.dataset import iter_fixed_batches
        yield from iter_fixed_batches(
            self.iter_blocks(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last)

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         sharding=None, drop_last: bool = True,
                         prefetch: int = 2,
                         batch_format: str = "numpy") -> Iterator[Any]:
        """Device-fed batches (see ``Dataset.iter_jax_batches``)."""
        from ray_tpu.data.dataset import iter_device_batches
        if batch_format != "numpy":
            raise ValueError(
                "iter_jax_batches requires batch_format='numpy'")
        it = self.iter_batches(batch_size=batch_size,
                               batch_format=batch_format,
                               drop_last=drop_last)
        yield from iter_device_batches(it, sharding=sharding,
                                       prefetch=prefetch)

    def iter_rows(self) -> Iterator[Any]:
        from ray_tpu.data.block import BlockAccessor
        for block in self.iter_blocks():
            yield from BlockAccessor.for_block(block).to_pylist()

    def count(self) -> int:
        """Row count of one epoch of this consumer's stream (drains it)."""
        from ray_tpu.data.block import BlockAccessor
        return sum(BlockAccessor.for_block(b).num_rows()
                   for b in self.iter_blocks())

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def shutdown(self) -> None:
        """Tear down the shared coordinator actor."""
        try:
            ray_tpu.kill(self._coord)
        except Exception:  # noqa: BLE001
            pass

    def __reduce__(self):
        # worker-side copies share the coordinator but never own it
        return (DataIterator, (self._coord, self._split, self._epoch))
