"""Sample packing: documents -> fixed ``[B, S]`` batches with
segment-aware masking.

The r06 lane-packing idiom applied to samples instead of attention
heads: a padded-per-document batch wastes every pad position's FLOPs,
so the packer fills each ``[S]`` row with as many whole documents as
fit, and the attention mask (threaded through ``models/gpt.py`` /
``ops/attention.py`` as ``segment_ids``) keeps co-packed documents
from attending to each other.  Each batch carries:

- ``tokens``      [B, S] int32 — concatenated documents, 0-padded;
- ``targets``     [B, S] int32 — next token *within the same segment*;
  the last position of every document and all padding are ``-1`` (the
  CE masking convention);
- ``segment_ids`` [B, S] int32 — 1-based per-row document index, 0 on
  padding (the attention-mask key: attend iff equal and nonzero);
- ``positions``   [B, S] int32 — position *within the document* (RoPE
  restarts at every document start), 0 on padding.

Determinism/robustness contract: the packer is a plain state machine
over an ordered document stream — its full state (open rows + the
partial row) serializes into the stream cursor via :meth:`state_dict`
/ :meth:`load_state`, so a resumed stream rebuilds mid-batch residue
exactly and replays the identical batch sequence.  Documents longer
than ``S`` are truncated to ``S`` (counted in ``truncated``); a
document is never split across rows — exactly-once accounting stays
document-granular.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class PackedBatch:
    """One assembled batch plus the bookkeeping the tests audit."""
    tokens: np.ndarray        # [B, S] int32
    targets: np.ndarray       # [B, S] int32 (-1 = masked)
    segment_ids: np.ndarray   # [B, S] int32 (0 = pad)
    positions: np.ndarray     # [B, S] int32
    # (row, col, doc_id, n_tokens) per packed document — the
    # exactly-once audit trail, host-side only
    spans: List[Tuple[int, int, int, int]]

    @property
    def packed_tokens(self) -> int:
        """Non-pad tokens in the batch (the FLOPs actually spent on
        data; pad positions are the reclaimable waste)."""
        return int((self.segment_ids > 0).sum())

    def as_train_batch(self, *, with_segments: bool = True
                       ) -> Dict[str, np.ndarray]:
        """The train-step batch dict.  ``with_segments=False`` (the
        unpacked one-doc-per-row arm) omits ``segment_ids``/
        ``positions``: trailing padding behind a single causal segment
        is already unreachable and its targets are ``-1``, so the
        plain-batch pytree works everywhere — including the pipeline-
        parallel and overlap trainers that decline the mask."""
        if not with_segments:
            return {"tokens": self.tokens, "targets": self.targets}
        return {"tokens": self.tokens, "targets": self.targets,
                "segment_ids": self.segment_ids,
                "positions": self.positions}


class SamplePacker:
    """Greedy whole-document packer with serializable residue.

    ``add(doc_id, tokens)`` feeds one document; ``ready`` /
    ``pop_batch()`` emit once ``batch_size`` rows have closed.  A row
    closes only when the next document does not fit (greedy,
    deterministic); ``flush()`` force-closes the residue at end of
    stream.  ``pack=False`` gives every document its own row — the
    unpacked A/B arm, same interface.
    """

    def __init__(self, batch_size: int, seq_len: int, *,
                 pack: bool = True):
        if batch_size < 1 or seq_len < 2:
            raise ValueError(f"need batch_size >= 1 and seq_len >= 2, "
                             f"got B={batch_size} S={seq_len}")
        self.B = int(batch_size)
        self.S = int(seq_len)
        self.pack = bool(pack)
        self.truncated = 0
        # closed rows waiting for a full batch; each row is a list of
        # (doc_id, [tokens...]) segments
        self._rows: List[List[Tuple[int, List[int]]]] = []
        self._cur: List[Tuple[int, List[int]]] = []
        self._cur_len = 0

    # ----------------------------------------------------------- feed
    def _close_row(self) -> None:
        self._rows.append(self._cur)
        self._cur = []
        self._cur_len = 0

    def add(self, doc_id: int, tokens: np.ndarray) -> None:
        toks = [int(t) for t in tokens[:self.S]]
        if len(tokens) > self.S:
            self.truncated += 1
        if not toks:
            return
        if not self.pack:
            self._rows.append([(int(doc_id), toks)])
            return
        if self._cur_len + len(toks) > self.S:
            self._close_row()
        self._cur.append((int(doc_id), toks))
        self._cur_len += len(toks)

    def flush(self) -> None:
        """End of stream: close the partial row so a final short batch
        can drain (padded with all-pad rows by :meth:`pop_batch`)."""
        if self._cur:
            self._close_row()

    # ----------------------------------------------------------- emit
    @property
    def ready(self) -> bool:
        return len(self._rows) >= self.B

    def pending_rows(self) -> int:
        return len(self._rows) + (1 if self._cur else 0)

    def pop_batch(self, *, allow_partial: bool = False
                  ) -> Optional[PackedBatch]:
        """Assemble ``[B, S]`` arrays from the oldest ``B`` closed rows
        (``allow_partial`` pads the batch with empty rows — the
        end-of-stream drain)."""
        if not self.ready and not (allow_partial and self._rows):
            return None
        rows, self._rows = self._rows[:self.B], self._rows[self.B:]
        B, S = self.B, self.S
        tokens = np.zeros((B, S), np.int32)
        targets = np.full((B, S), -1, np.int32)
        segment_ids = np.zeros((B, S), np.int32)
        positions = np.zeros((B, S), np.int32)
        spans: List[Tuple[int, int, int, int]] = []
        for r, row in enumerate(rows):
            col = 0
            for seg, (doc_id, toks) in enumerate(row, start=1):
                n = len(toks)
                tokens[r, col:col + n] = toks
                targets[r, col:col + n - 1] = toks[1:]
                segment_ids[r, col:col + n] = seg
                positions[r, col:col + n] = np.arange(n)
                spans.append((r, col, doc_id, n))
                col += n
        return PackedBatch(tokens, targets, segment_ids, positions,
                           spans)

    # ---------------------------------------------------------- cursor
    def state_dict(self) -> Dict[str, Any]:
        """JSON-able residue: closed-but-unemitted rows + the partial
        row + the truncation counter (everything a resumed stream
        needs to replay the identical next batch)."""
        return {
            "rows": [[[d, list(t)] for d, t in row]
                     for row in self._rows],
            "cur": [[d, list(t)] for d, t in self._cur],
            "truncated": self.truncated,
        }

    def load_state(self, state: Dict[str, Any]) -> None:
        self._rows = [[(int(d), [int(x) for x in t]) for d, t in row]
                      for row in state.get("rows", [])]
        self._cur = [(int(d), [int(x) for x in t])
                     for d, t in state.get("cur", [])]
        self._cur_len = sum(len(t) for _, t in self._cur)
        self.truncated = int(state.get("truncated", 0))
