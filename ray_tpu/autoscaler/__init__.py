"""Autoscaler — demand-driven node provisioning.

Parity: reference autoscaler v1/v2
(``python/ray/autoscaler/_private/autoscaler.py:1``,
``autoscaler/v2``): a monitor loop reads cluster load from the control
plane (per-node queue depth piggybacked on heartbeats), launches worker
nodes through a ``NodeProvider`` while demand is sustained, and reaps
nodes that stay idle past ``idle_timeout_s``.

Providers: ``LocalNodeProvider`` spawns real extra node-manager
processes on this host (the multi-node-on-one-host simulation the test
suite uses everywhere); a cloud provider for TPU pods implements the
same three methods against its VM API.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu


class NodeProvider:
    """Minimal provider surface (reference: node_provider.py)."""

    def create_node(self) -> bytes:
        raise NotImplementedError

    def terminate_node(self, node_id: bytes) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[bytes]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Real extra node processes on this host (cluster_utils parity)."""

    def __init__(self, worker_resources: Optional[Dict[str, float]] = None):
        from ray_tpu._private.worker import global_node
        self._node = global_node()
        self.worker_resources = worker_resources or {"CPU": 2.0}
        self._nodes: List[bytes] = []

    def create_node(self) -> bytes:
        res = dict(self.worker_resources)
        cpus = res.pop("CPU", 1.0)
        tpus = res.pop("TPU", 0.0)
        node_id = self._node.add_node(num_cpus=cpus, num_tpus=tpus,
                                      resources=res or None)
        self._nodes.append(node_id)
        return node_id

    def terminate_node(self, node_id: bytes) -> None:
        self._node.remove_node(node_id)
        if node_id in self._nodes:
            self._nodes.remove(node_id)

    def non_terminated_nodes(self) -> List[bytes]:
        return list(self._nodes)


@dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 2
    # pending work must persist this long before a node launches
    upscale_delay_s: float = 1.0
    # a provider node with zero load/zero busy resources this long is
    # terminated
    idle_timeout_s: float = 10.0
    tick_s: float = 0.5


class StandardAutoscaler:
    """Monitor thread: scale the provider between min and max workers."""

    def __init__(self, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None):
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self._stop = threading.Event()
        self._pending_since: Optional[float] = None
        self._idle_since: Dict[bytes, float] = {}
        self._thread: Optional[threading.Thread] = None
        self.events: List[str] = []  # human-readable scaling decisions

    # -- cluster state -------------------------------------------------
    @staticmethod
    def _nodes() -> List[Dict[str, Any]]:
        from ray_tpu._private.worker import global_worker
        return global_worker().cp.list_nodes()

    def start(self) -> None:
        for _ in range(self.config.min_workers):
            self.provider.create_node()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.config.tick_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — keep the monitor alive
                pass

    def _tick(self) -> None:
        now = time.monotonic()
        nodes = {n["node_id"]: n for n in self._nodes()
                 if n.get("state") == "ALIVE"}
        pending = sum((n.get("load") or {}).get("num_pending", 0)
                      for n in nodes.values())
        # the max_workers bound counts every provider node, including
        # ones still booting (not ALIVE yet) — otherwise slow startup
        # lets sustained demand overshoot the cap
        provisioned = self.provider.non_terminated_nodes()
        managed = [nid for nid in provisioned if nid in nodes]

        # ---- scale up: sustained unservable demand
        if pending > 0:
            if self._pending_since is None:
                self._pending_since = now
            elif (now - self._pending_since >=
                  self.config.upscale_delay_s
                  and len(provisioned) < self.config.max_workers):
                # record the decision before the (blocking) launch —
                # node startup can take seconds and observability should
                # reflect when scaling was *chosen*
                self.events.append(f"up: +node (pending={pending})")
                self._pending_since = None
                node_id = self.provider.create_node()
                self.events.append(
                    f"up: node {node_id.hex()[:8]} ready")
        else:
            self._pending_since = None

        # ---- scale down: provider nodes idle past the timeout
        alive_count = len(managed)
        for nid in list(managed):
            info = nodes[nid]
            load = (info.get("load") or {}).get("num_pending", 0)
            avail = info.get("resources_available") or {}
            total = info.get("resources_total") or {}
            busy = any(avail.get(k, 0) < total.get(k, 0) for k in total)
            if load == 0 and not busy:
                self._idle_since.setdefault(nid, now)
                if (now - self._idle_since[nid] >=
                        self.config.idle_timeout_s
                        and alive_count > self.config.min_workers):
                    self.provider.terminate_node(nid)
                    self.events.append(f"down: -node {nid.hex()[:8]}")
                    self._idle_since.pop(nid, None)
                    alive_count -= 1
            else:
                self._idle_since.pop(nid, None)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


def request_resources(num_cpus: float = 0,
                      bundles: Optional[List[Dict]] = None) -> None:
    """API parity stub for ``ray.autoscaler.sdk.request_resources``:
    demand is inferred from queue depth; explicit requests are recorded
    as a KV hint for operators."""
    import json

    from ray_tpu._private.worker import global_worker
    global_worker().cp.kv_put(
        b"autoscaler_request",
        json.dumps({"num_cpus": num_cpus,
                    "bundles": bundles or []}).encode(),
        namespace="_autoscaler")
