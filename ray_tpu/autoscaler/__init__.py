"""Autoscaler — demand-driven node provisioning.

Parity: reference autoscaler v1/v2
(``python/ray/autoscaler/_private/autoscaler.py:1``,
``autoscaler/v2``): a monitor loop reads cluster load from the control
plane (per-node queue depth piggybacked on heartbeats), launches worker
nodes through a ``NodeProvider`` while demand is sustained, and reaps
nodes that stay idle past ``idle_timeout_s``.

Providers: ``LocalNodeProvider`` spawns real extra node-manager
processes on this host (the multi-node-on-one-host simulation the test
suite uses everywhere); a cloud provider for TPU pods implements the
same three methods against its VM API.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu


class NodeProvider:
    """Minimal provider surface (reference: node_provider.py +
    ``available_node_types`` from the cluster config)."""

    def node_types(self) -> Dict[str, Dict[str, float]]:
        """Launchable node shapes: type name -> resource dict."""
        return {"default": {"CPU": 1.0}}

    def create_node(self, node_type: str = "default") -> bytes:
        raise NotImplementedError

    def terminate_node(self, node_id: bytes) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[bytes]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Real extra node processes on this host (cluster_utils parity)."""

    def __init__(self, worker_resources: Optional[Dict[str, float]] = None,
                 node_types: Optional[Dict[str, Dict[str, float]]] = None):
        from ray_tpu._private.worker import global_node
        self._node = global_node()
        self.worker_resources = worker_resources or {"CPU": 2.0}
        self._types = node_types or {"default": dict(self.worker_resources)}
        self._nodes: List[bytes] = []

    def node_types(self) -> Dict[str, Dict[str, float]]:
        return {k: dict(v) for k, v in self._types.items()}

    def create_node(self, node_type: str = "default") -> bytes:
        res = dict(self._types.get(node_type, self.worker_resources))
        cpus = res.pop("CPU", 1.0)
        tpus = res.pop("TPU", 0.0)
        node_id = self._node.add_node(num_cpus=cpus, num_tpus=tpus,
                                      resources=res or None)
        self._nodes.append(node_id)
        return node_id

    def terminate_node(self, node_id: bytes) -> None:
        self._node.remove_node(node_id)
        if node_id in self._nodes:
            self._nodes.remove(node_id)

    def non_terminated_nodes(self) -> List[bytes]:
        return list(self._nodes)


@dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 2
    # pending work must persist this long before a node launches
    upscale_delay_s: float = 1.0
    # a provider node with zero load/zero busy resources this long is
    # terminated
    idle_timeout_s: float = 10.0
    tick_s: float = 0.5


class StandardAutoscaler:
    """Monitor thread: scale the provider between min and max workers."""

    def __init__(self, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None,
                 engine: str = "v1"):
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self._stop = threading.Event()
        self._pending_since: Optional[float] = None
        self._idle_since: Dict[bytes, float] = {}
        self._thread: Optional[threading.Thread] = None
        self.events: List[str] = []  # human-readable scaling decisions
        # engine="v2": demand decisions stay here, but launches and
        # terminations flow through the instance reconciler, whose
        # state machine heals stuck/failed launches across ticks
        # (reference: autoscaler/v2/instance_manager/reconciler.py)
        self.reconciler = None
        if engine == "v2":
            from ray_tpu.autoscaler.v2 import InstanceReconciler
            self.reconciler = InstanceReconciler(provider)

    # -- cluster state -------------------------------------------------
    @staticmethod
    def _nodes() -> List[Dict[str, Any]]:
        from ray_tpu._private.worker import global_worker
        return global_worker().cp.list_nodes()

    def start(self) -> None:
        # advertise launchable shapes so node managers keep queueing
        # tasks this autoscaler could satisfy (instead of failing them
        # as infeasible) and their demand reaches the heartbeats
        import json

        from ray_tpu._private.worker import global_worker
        try:
            global_worker().cp.kv_put(
                b"node_types",
                json.dumps(self.provider.node_types()).encode(),
                namespace="_autoscaler", overwrite=True)
        except Exception:  # noqa: BLE001 - registry is best-effort
            pass
        if self.reconciler is not None:
            self.reconciler.set_target("default", self.config.min_workers)
            self.reconciler.start()
        else:
            for _ in range(self.config.min_workers):
                self.provider.create_node()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.config.tick_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — keep the monitor alive
                pass

    def _tick(self) -> None:
        now = time.monotonic()
        nodes = {n["node_id"]: n for n in self._nodes()
                 if n.get("state") == "ALIVE"}
        pending = sum((n.get("load") or {}).get("num_pending", 0)
                      for n in nodes.values())
        # the max_workers bound counts every provider node, including
        # ones still booting (not ALIVE yet) — otherwise slow startup
        # lets sustained demand overshoot the cap.  In v2 mode the
        # launch is async, so instances the reconciler is still
        # materializing count too (they aren't provider-visible yet).
        provisioned = self.provider.non_terminated_nodes()
        managed = [nid for nid in provisioned if nid in nodes]
        in_flight = (self.reconciler.live_count()
                     if self.reconciler is not None
                     else len(provisioned))

        # ---- scale up: sustained unservable demand, matched by SHAPE
        # (reference: resource_demand_scheduler.py — bin-pack pending
        # bundles against launchable node types, not raw queue depth)
        if pending > 0:
            if self._pending_since is None:
                self._pending_since = now
            elif (now - self._pending_since >=
                  self.config.upscale_delay_s
                  and max(len(provisioned), in_flight)
                  < self.config.max_workers):
                node_type = self._pick_node_type(nodes.values())
                if node_type is not None:
                    # record the decision before the (blocking) launch —
                    # node startup can take seconds and observability
                    # should reflect when scaling was *chosen*
                    self.events.append(
                        f"up: +{node_type} (pending={pending})")
                    self._pending_since = None
                    if self.reconciler is not None:
                        # async: the reconciler launches, retries a
                        # stuck/failed create, and reports RAY_RUNNING
                        # once the node joins
                        self.reconciler.bump_target(node_type, +1)
                    else:
                        node_id = self.provider.create_node(node_type)
                        self.events.append(
                            f"up: node {node_id.hex()[:8]} ready")
        else:
            self._pending_since = None

        # ---- scale down: provider nodes idle past the timeout
        self._scale_down(nodes, managed, now)

    def _pick_node_type(self, node_infos) -> Optional[str]:
        """Bin-pack the heartbeat demand vector against existing
        capacity; pick the node type satisfying the most unfulfilled
        bundles (ties: fewest resources).  Returns None when nothing
        pending fits any launchable type (those bundles are logged as
        infeasible)."""
        demand: List[Dict[str, float]] = []
        for info in node_infos:
            for s in (info.get("load") or {}).get("pending_shapes", []):
                demand.extend([s["resources"]] * min(int(s["count"]), 64))
        types = self.provider.node_types()
        if not demand:
            # num_pending counted dep-waiting or just-drained work but
            # the shape vector is empty: launching an arbitrary type
            # would be a blind guess — wait for real shape demand
            return None
        # virtually pack demand onto existing nodes' available resources
        virtual = [dict(info.get("resources_available") or {})
                   for info in node_infos]
        unfulfilled: List[Dict[str, float]] = []
        for bundle in demand:
            for avail in virtual:
                if all(avail.get(k, 0.0) >= v for k, v in bundle.items()):
                    for k, v in bundle.items():
                        avail[k] = avail.get(k, 0.0) - v
                    break
            else:
                unfulfilled.append(bundle)
        if not unfulfilled:
            return None
        best, best_score = None, (0, 0.0)
        for name, shape in types.items():
            cap = dict(shape)
            served = 0
            for bundle in unfulfilled:
                if all(cap.get(k, 0.0) >= v for k, v in bundle.items()):
                    for k, v in bundle.items():
                        cap[k] -= v
                    served += 1
            score = (served, -sum(shape.values()))
            if served > 0 and score > best_score:
                best, best_score = name, score
        if best is None:
            infeasible = [b for b in unfulfilled
                          if not any(
                              all(shape.get(k, 0) >= v
                                  for k, v in b.items())
                              for shape in types.values())]
            if infeasible:
                msg = f"infeasible: {infeasible[0]} fits no node type"
                if not self.events or self.events[-1] != msg:
                    self.events.append(msg)
        return best

    def _scale_down(self, nodes, managed, now) -> None:
        alive_count = len(managed)
        for nid in list(managed):
            info = nodes[nid]
            load = (info.get("load") or {}).get("num_pending", 0)
            avail = info.get("resources_available") or {}
            total = info.get("resources_total") or {}
            busy = any(avail.get(k, 0) < total.get(k, 0) for k in total)
            if load == 0 and not busy:
                self._idle_since.setdefault(nid, now)
                if (now - self._idle_since[nid] >=
                        self.config.idle_timeout_s
                        and alive_count > self.config.min_workers):
                    if self.reconciler is not None:
                        if not self.reconciler.release_node(nid):
                            # instance not releasable yet (reconciler
                            # hasn't observed the node): retry next tick
                            continue
                    else:
                        self.provider.terminate_node(nid)
                    self.events.append(f"down: -node {nid.hex()[:8]}")
                    self._idle_since.pop(nid, None)
                    alive_count -= 1
            else:
                self._idle_since.pop(nid, None)

    def stop(self) -> None:
        self._stop.set()
        if self.reconciler is not None:
            self.reconciler.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # withdraw the shape registry: with no autoscaler to provision
        # them, unservable shapes must fail fast again
        from ray_tpu._private.worker import global_worker
        try:
            global_worker().cp.kv_del(b"node_types",
                                      namespace="_autoscaler")
        except Exception:  # noqa: BLE001 - session may be gone
            pass


def request_resources(num_cpus: float = 0,
                      bundles: Optional[List[Dict]] = None) -> None:
    """API parity stub for ``ray.autoscaler.sdk.request_resources``:
    demand is inferred from queue depth; explicit requests are recorded
    as a KV hint for operators."""
    import json

    from ray_tpu._private.worker import global_worker
    global_worker().cp.kv_put(
        b"autoscaler_request",
        json.dumps({"num_cpus": num_cpus,
                    "bundles": bundles or []}).encode(),
        namespace="_autoscaler")
