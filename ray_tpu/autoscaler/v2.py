"""Autoscaler v2 — reconciler-style instance manager.

Parity: ``python/ray/autoscaler/v2/instance_manager/reconciler.py``:
instead of v1's imperative scale-up/down decisions, v2 keeps a table of
*instances*, each walking an explicit state machine

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
                                   \\-> TERMINATING -> TERMINATED
           (REQUESTED | ALLOCATED stuck past timeout -> retried/FAILED)

and every tick *reconciles* the table against (a) the provider's view
and (b) the cluster's live node set.  Crashes between decision and
effect are healed by the next tick instead of leaking instances — the
property v1 loops lack.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
FAILED = "FAILED"


@dataclass
class Instance:
    instance_id: str
    node_type: str
    state: str = QUEUED
    node_id: Optional[bytes] = None
    updated_at: float = field(default_factory=time.monotonic)
    retries: int = 0

    def to(self, state: str) -> None:
        self.state = state
        self.updated_at = time.monotonic()


@dataclass
class ReconcilerConfig:
    request_timeout_s: float = 30.0    # stuck REQUESTED -> retry
    allocate_timeout_s: float = 60.0   # ALLOCATED but node never ALIVE
    max_retries: int = 2
    tick_s: float = 0.5


class InstanceReconciler:
    """Drive instance states toward per-type targets.

    ``provider`` needs ``create_node(node_type) -> node_id`` and
    ``terminate_node(node_id)`` (the v1 ``NodeProvider`` surface).
    ``list_cluster_nodes`` returns the control plane's node table; it
    is injected so the reconciler unit-tests without a runtime.
    """

    def __init__(self, provider, config: Optional[ReconcilerConfig] = None,
                 list_cluster_nodes: Optional[Callable] = None):
        self.provider = provider
        self.config = config or ReconcilerConfig()
        self.instances: Dict[str, Instance] = {}
        self.targets: Dict[str, int] = {}
        self.events: List[str] = []
        self._list_nodes = list_cluster_nodes or self._default_nodes
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _default_nodes() -> List[Dict[str, Any]]:
        from ray_tpu._private.worker import global_worker
        return global_worker().cp.list_nodes()

    # ------------------------------------------------------------- API
    def set_target(self, node_type: str, count: int) -> None:
        with self._lock:
            self.targets[node_type] = count

    def bump_target(self, node_type: str, delta: int) -> None:
        with self._lock:
            self.targets[node_type] = max(
                0, self.targets.get(node_type, 0) + delta)

    def live_count(self) -> int:
        """Instances being launched or running — callers enforcing a
        max-nodes cap must count these, not just provider-visible
        nodes, or demand overshoots the cap during a slow launch."""
        live = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING)
        with self._lock:
            return sum(1 for i in self.instances.values()
                       if i.state in live)

    def release_node(self, node_id: bytes) -> bool:
        """Terminate the SPECIFIC instance running ``node_id`` (idle
        scale-down chooses its victim; a bare target decrement would
        let the reconciler pick an arbitrary one).  False when no
        releasable instance matches (caller must not record a
        termination that did not happen)."""
        with self._lock:
            for inst in self.instances.values():
                if inst.node_id == node_id \
                        and inst.state in (RAY_RUNNING, ALLOCATED):
                    inst.to(TERMINATING)
                    self.targets[inst.node_type] = max(
                        0, self.targets.get(inst.node_type, 1) - 1)
                    self._log(f"{inst.instance_id[:8]} released "
                              f"({node_id.hex()[:8]} idle)")
                    return True
        return False

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler-v2")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.config.tick_s):
            try:
                self.reconcile()
            except Exception:  # noqa: BLE001 — heal next tick
                pass

    # ------------------------------------------------------ reconcile
    def _log(self, msg: str) -> None:
        self.events.append(msg)

    def reconcile(self) -> None:
        """One pass: sync with cluster state, heal stuck instances,
        then converge instance counts toward the targets."""
        now = time.monotonic()
        alive = {n["node_id"] for n in self._list_nodes()
                 if n.get("state") == "ALIVE"}
        cfg = self.config
        with self._lock:
            live_states = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING)
            # 1. observe: allocated instances whose node joined/left
            for inst in self.instances.values():
                if inst.state == ALLOCATED and inst.node_id in alive:
                    inst.to(RAY_RUNNING)
                    self._log(f"{inst.instance_id[:8]} RAY_RUNNING")
                elif inst.state == RAY_RUNNING and \
                        inst.node_id not in alive:
                    # node died underneath us: release the instance
                    inst.to(TERMINATING)
                    self._log(f"{inst.instance_id[:8]} node died")
            # 2. heal: stuck transitions retry (bounded) or fail
            for inst in self.instances.values():
                age = now - inst.updated_at
                if inst.state == REQUESTED and \
                        age > cfg.request_timeout_s:
                    self._retry_or_fail(inst, "request timed out")
                elif inst.state == ALLOCATED and \
                        age > cfg.allocate_timeout_s:
                    # provider gave us a node that never joined: drop
                    # it and retry
                    self._terminate_quiet(inst)
                    self._retry_or_fail(inst, "node never joined")
            # 3. converge per type
            for node_type, want in self.targets.items():
                have = [i for i in self.instances.values()
                        if i.node_type == node_type
                        and i.state in live_states]
                for _ in range(want - len(have)):
                    iid = uuid.uuid4().hex
                    self.instances[iid] = Instance(iid, node_type)
                    self._log(f"{iid[:8]} QUEUED ({node_type})")
                for inst in have[want:] if len(have) > want else []:
                    inst.to(TERMINATING)
                    self._log(f"{inst.instance_id[:8]} excess")
            # snapshot work outside the lock
            to_request = [i for i in self.instances.values()
                          if i.state == QUEUED]
            to_terminate = [i for i in self.instances.values()
                            if i.state == TERMINATING]
            for inst in to_request:
                inst.to(REQUESTED)
        # 4. effect (provider calls block: outside the lock)
        for inst in to_request:
            try:
                node_id = self.provider.create_node(inst.node_type)
                with self._lock:
                    inst.node_id = node_id
                    inst.to(ALLOCATED)
                self._log(f"{inst.instance_id[:8]} ALLOCATED "
                          f"{node_id.hex()[:8]}")
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    self._retry_or_fail(inst, f"create failed: {e}")
        for inst in to_terminate:
            self._terminate_quiet(inst)
            with self._lock:
                inst.to(TERMINATED)
            self._log(f"{inst.instance_id[:8]} TERMINATED")

    def _retry_or_fail(self, inst: Instance, why: str) -> None:
        inst.retries += 1
        if inst.retries > self.config.max_retries:
            inst.to(FAILED)
            # surrender the demand slot: leaving the target in place
            # would queue a fresh instance every tick against a
            # provider that keeps failing (quota, bad type)
            self.targets[inst.node_type] = max(
                0, self.targets.get(inst.node_type, 1) - 1)
            self._log(f"{inst.instance_id[:8]} FAILED: {why}")
        else:
            inst.node_id = None
            inst.to(QUEUED)
            self._log(f"{inst.instance_id[:8]} retry "
                      f"{inst.retries}: {why}")

    def _terminate_quiet(self, inst: Instance) -> None:
        if inst.node_id is None:
            return
        try:
            self.provider.terminate_node(inst.node_id)
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        with self._lock:
            by_state: Dict[str, int] = {}
            for inst in self.instances.values():
                by_state[inst.state] = by_state.get(inst.state, 0) + 1
            return {"instances": by_state, "targets": dict(self.targets),
                    "events": list(self.events[-50:])}
