"""One fleet replica: an inference engine plus health/drain state.

The fleet layer is host-driven by design (the Podracer pattern one
level up): the router owns the tick loop and calls :meth:`step` on
every replica with work, so a deterministic ``RAY_TPU_FAULTS`` plan
reproduces the same death/wedge point every run — the property the
chaos acceptance tests are built on.  A replica wraps one
:class:`~ray_tpu.inference.engine.InferenceEngine` (replicas of one
fleet share the executable cache, so scale-up and restart compile
nothing) and carries the three health signals the router and
reconciler consume:

- **alive**: flips False when a step raises (the ``serve.replica``
  chaos site fires at the top of :meth:`step`, before any engine
  mutation — an injected death leaves the engine state consistent for
  the host-side reap);
- **latency**: an EWMA of tick wall seconds (the ``serve.tick`` /
  ``serve.tick[<replica_id>]`` slowdown sites stretch exactly this
  window, so an injected gray failure is visible to the same signal a
  real one would be) — the router's health score: replicas past
  ``RAY_TPU_FLEET_SLOW_FACTOR``x the fleet median are demoted from
  routing and reported DEGRADED to the reconciler;
- **wedged**: the r15 :class:`~ray_tpu.resilience.watchdog.
  EngineWatchdog` signal, probed manually by the router's poll loop
  (no background thread — deterministic under test clocks);
- **draining**: admission stopped (``submit`` raises the typed
  :class:`~ray_tpu.inference.serve_gpt.ReplicaDrainingError`, the
  router's immediate re-route signal) while in-flight sequences decode
  to completion — the zero-dropped-streams scale-down path.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu.inference.engine import InferenceEngine, StepEvent

# EWMA smoothing for the tick-latency health score: new = a*x + (1-a)*old.
# 0.25 converges on a sustained slowdown within ~8 ticks while a single
# slow tick (GC pause, one long prefill) decays away instead of demoting
# the replica — the blip-vs-sustained line the reconciler dwell also draws.
LATENCY_EWMA_ALPHA = 0.25
# An idle replica produces no fresh ticks, and demotion is exactly what
# stops its traffic — without decay a demoted-then-idle replica's frozen
# slow EWMA would keep it demoted forever and the reconciler's
# blip-recovers-to-RUNNING arm could never fire for replicas without
# continuous work.  Halving the score per 5 idle seconds lets a MILD
# transient (a few x the fleet median) age back under the demotion
# threshold and be re-probed by real traffic, while a severe outlier
# stays demoted past the reconciler's dwell (default 5 s) and is
# recycled — the severity of the score decides blip vs restart.  The
# half-life must stay of the dwell's order: a fast decay flaps
# demote/re-promote inside one routing episode (measured: it doubles
# demotions and wastes hedges in the `bench.py --gray` scenario).
LATENCY_IDLE_HALFLIFE_S = 5.0


class EngineReplica:
    """One engine behind the fleet router.

    ``watchdog_s`` arms a manual-probe wedge detector (the router
    calls :meth:`check` each poll; no thread, so tests drive it with
    explicit clocks).  ``replica_id`` must be unique within a fleet —
    the router keys stream bindings by ``(replica_id, rid)`` so a
    failed-over request's stale events can never leak into its
    stream.
    """

    def __init__(self, replica_id: str, engine: InferenceEngine, *,
                 watchdog_s: float = 0.0):
        self.id = replica_id
        self.engine = engine
        # r24 tracing: engine spans carry this replica's id, so a
        # cross-replica trace tree (disagg, failover) attributes each
        # span to the replica that did the work
        engine.trace_label = replica_id
        self.alive = True
        self.draining = False
        self.watchdog = None
        if watchdog_s:
            from ray_tpu.resilience.watchdog import EngineWatchdog
            # NOT .start()ed: the router's poll loop probes check()
            self.watchdog = EngineWatchdog(engine, timeout_s=watchdog_s)
        # test/chaos hook: a "wedged" replica has work but its step
        # stops ticking (the engine stamp freezes -> the watchdog
        # fires); real wedges are a hung device step, which host-sim
        # cannot produce in a single-threaded drive loop
        self._stalled = False
        self.reaped = False
        # prefix-digest memo, keyed by engine tick: registrations only
        # happen inside step() (which bumps ticks), so within one
        # router poll the digest is immutable — the routing hot path
        # must not rebuild an O(pages) frozenset per candidate per
        # request.  (A set_params prefix flush without a tick can
        # serve one stale digest: a routing-quality blip, never a
        # correctness one — admission re-walks the real index.)
        self._digest: Optional[frozenset] = None
        self._digest_ticks = -1
        # EWMA tick wall seconds (None until the first worked tick) —
        # the gray-failure health score.  _tick_t0 marks a step in
        # flight (concurrent router mode): its age is a live lower
        # bound on this tick's wall, so a sustained slowdown is
        # scoreable BEFORE the first slow tick even completes.
        self._latency_ewma: Optional[float] = None
        self._tick_t0: Optional[float] = None
        self._last_tick_done_ts = time.monotonic()

    # --------------------------------------------------------- admission
    def submit(self, prompt, *, max_new_tokens: int, sampling=None,
               eos_token=None, ttft_deadline_s=None,
               deadline_s=None, hold_pages: bool = False,
               trace_ctx=None) -> int:
        """Admit one request; raises the typed re-route signals
        (``ReplicaDrainingError`` / ``QueueFullError``) the router
        retries on, or ``ValueError`` for a request this fleet's
        geometry can never serve (the router fails the stream).
        ``hold_pages`` is the disagg prefill seam, ``trace_ctx`` the
        r24 tracing one (see :meth:`InferenceEngine.submit`)."""
        self._check_admittable()
        return self.engine.submit(prompt, max_new_tokens=max_new_tokens,
                                  sampling=sampling, eos_token=eos_token,
                                  ttft_deadline_s=ttft_deadline_s,
                                  deadline_s=deadline_s,
                                  hold_pages=hold_pages,
                                  trace_ctx=trace_ctx)

    def submit_import(self, handoff, *, max_new_tokens: int,
                      sampling=None, eos_token=None,
                      deadline_s=None) -> int:
        """Admit a KV handoff (the disagg decode seam) under the same
        alive/draining admission guards as :meth:`submit`."""
        self._check_admittable()
        return self.engine.import_submit(
            handoff, max_new_tokens=max_new_tokens, sampling=sampling,
            eos_token=eos_token, deadline_s=deadline_s)

    def _check_admittable(self) -> None:
        if not self.alive:
            raise RuntimeError(f"replica {self.id} is dead — the "
                               "router must not route to it")
        if self.draining:
            from ray_tpu.inference.serve_gpt import ReplicaDrainingError
            raise ReplicaDrainingError(
                f"replica {self.id} is draining: admission stopped, "
                "in-flight requests finishing — route elsewhere")

    # -------------------------------------------------------------- tick
    def step(self) -> List[StepEvent]:
        """One engine tick.  The ``serve.replica`` fault site fires
        BEFORE the engine steps (donated buffers untouched, scheduler
        consistent) and any raise — injected or real — marks the
        replica dead before propagating, so the router's failover path
        sees a consistent corpse.  The ``serve.tick`` slowdown sites
        (fleet-wide, and ``serve.tick[<id>]`` addressing this replica
        alone) stretch the timed window, so injected gray failure
        lands in the same EWMA a genuinely slow device would."""
        from ray_tpu.util import chaos
        if self._stalled:
            return []                  # wedge: work pending, no tick
        t0 = time.monotonic()
        self._tick_t0 = t0
        try:
            chaos.maybe_fail("serve.replica")
            chaos.maybe_fail("serve.tick")
            chaos.maybe_fail(f"serve.tick[{self.id}]")
            events = self.engine.step()
        except BaseException:
            self.alive = False
            raise
        finally:
            self._tick_t0 = None
            self._last_tick_done_ts = time.monotonic()
        wall = time.monotonic() - t0
        self._latency_ewma = wall if self._latency_ewma is None else (
            LATENCY_EWMA_ALPHA * wall
            + (1.0 - LATENCY_EWMA_ALPHA) * self._latency_ewma)
        return events

    # ------------------------------------------------------------ health
    @property
    def wedged(self) -> bool:
        return self.watchdog is not None and self.watchdog.wedges > 0

    @property
    def wedges(self) -> int:
        return self.watchdog.wedges if self.watchdog is not None else 0

    def check(self, now: Optional[float] = None) -> None:
        """Probe the watchdog (the router calls this each poll)."""
        if self.watchdog is not None:
            self.watchdog.check(now)

    def stall(self) -> None:
        """Wedge this replica (test/driver hook): work stops ticking,
        the engine stamps freeze, and the next watchdog probe past the
        budget declares the wedge."""
        self._stalled = True

    def has_work(self) -> bool:
        return self.alive and self.engine.has_work()

    def queue_depth(self) -> int:
        """Waiting + active — the pow-2 load signal."""
        sched = self.engine.scheduler
        return len(sched.waiting) + len(sched.active)

    def waiting_depth(self) -> int:
        return len(self.engine.scheduler.waiting)

    def latency_score(self) -> float:
        """EWMA tick wall seconds; 0.0 until the first worked tick
        (an unmeasured replica is presumed healthy — a cold replica
        must not start its life demoted).  A step in flight raises the
        score to at least its age: a tick that has already run 0.4 s
        *is* 0.4 s slow — demotion must not wait for it to finish
        (benign cross-thread read: t0 is a monotonic stamp).  An
        *idle* replica's score decays (``LATENCY_IDLE_HALFLIFE_S``):
        stale slowness evidence must not demote forever."""
        score = self._latency_ewma or 0.0
        t0 = self._tick_t0
        now = time.monotonic()
        if t0 is not None:
            return max(score, now - t0)
        if score > 0.0 and not self.has_work():
            score *= 0.5 ** ((now - self._last_tick_done_ts)
                             / LATENCY_IDLE_HALFLIFE_S)
        return score

    def prefix_digest(self) -> frozenset:
        ticks = self.engine.ticks
        if self._digest is None or self._digest_ticks != ticks:
            self._digest = self.engine.prefix_digest()
            self._digest_ticks = ticks
        return self._digest

    def adapter_digest(self) -> frozenset:
        """Resident tenant model_ids (r25): the router's adapter-
        affinity signal — a request for a resident tenant skips the
        store fetch + bank install entirely.  Cheap enough (a few
        entries, bounded by the bank) not to memo like the prefix
        digest."""
        return self.engine.adapter_digest()

    # ------------------------------------------------------------- drain
    def drain(self) -> None:
        self.draining = True

    @property
    def drained(self) -> bool:
        return self.draining and not self.engine.has_work()

    # -------------------------------------------------------------- reap
    def reap(self) -> int:
        """Host-side teardown for a dead/wedged replica being replaced:
        retire every request so slots/pages/prefix refcounts release
        (the r15 dead-actor precedent — the corpse must audit clean
        before it is dropped).  Returns retired-request count."""
        self.reaped = True
        return self.engine.drain_requests()

    def leak_free(self) -> bool:
        """Fleet-wide leak audit: every slot free, every page either
        free or parked idle in the prefix pool, nothing in flight —
        and (r23) the engine's tier inventory partitions exactly, with
        no store fetch left checked out."""
        sched = self.engine.scheduler
        return (not sched.active and not sched.waiting
                and len(sched.free_slots) == self.engine.slots
                and sched.allocator.free_count
                == sched.allocator.num_pages - 1
                and self.engine.leak_free())

    def tier_hits(self, chain_hashes) -> "tuple[int, int]":
        """How far this replica's warm tiers cover a prompt's chained
        page hashes: ``(n_hbm, n_dram)`` — consecutive leading pages
        resident in HBM, then consecutive pages sitting in the host-
        DRAM pool.  The router's tier-aware cost signal: an HBM hit is
        a refcount bump, a DRAM hit pays a host->device copy, and the
        store is deliberately absent — any replica can fetch a store
        page at the same price, so store coverage never differentiates
        candidates."""
        digest = self.prefix_digest()
        n_hbm = 0
        for h in chain_hashes:
            if h not in digest:
                break
            n_hbm += 1
        n_dram = 0
        pool = self.engine.host_pool
        if pool is not None:
            ver = self.engine.param_version
            for h in chain_hashes[n_hbm:]:
                if (h, ver) not in pool:
                    break
                n_dram += 1
        return n_hbm, n_dram

    def stats(self) -> Dict[str, Any]:
        out = self.engine.stats()
        out["replica"] = self.id
        out["alive"] = self.alive
        out["draining"] = self.draining
        out["wedges"] = self.wedges
        out["last_wedge_ts"] = (self.watchdog.last_wedge_ts
                                if self.watchdog is not None else None)
        out["latency_score"] = self.latency_score()
        return out
