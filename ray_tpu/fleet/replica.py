"""One fleet replica: an inference engine plus health/drain state.

The fleet layer is host-driven by design (the Podracer pattern one
level up): the router owns the tick loop and calls :meth:`step` on
every replica with work, so a deterministic ``RAY_TPU_FAULTS`` plan
reproduces the same death/wedge point every run — the property the
chaos acceptance tests are built on.  A replica wraps one
:class:`~ray_tpu.inference.engine.InferenceEngine` (replicas of one
fleet share the executable cache, so scale-up and restart compile
nothing) and carries the three health signals the router and
reconciler consume:

- **alive**: flips False when a step raises (the ``serve.replica``
  chaos site fires at the top of :meth:`step`, before any engine
  mutation — an injected death leaves the engine state consistent for
  the host-side reap);
- **wedged**: the r15 :class:`~ray_tpu.resilience.watchdog.
  EngineWatchdog` signal, probed manually by the router's poll loop
  (no background thread — deterministic under test clocks);
- **draining**: admission stopped (``submit`` raises the typed
  :class:`~ray_tpu.inference.serve_gpt.ReplicaDrainingError`, the
  router's immediate re-route signal) while in-flight sequences decode
  to completion — the zero-dropped-streams scale-down path.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.inference.engine import InferenceEngine, StepEvent


class EngineReplica:
    """One engine behind the fleet router.

    ``watchdog_s`` arms a manual-probe wedge detector (the router
    calls :meth:`check` each poll; no thread, so tests drive it with
    explicit clocks).  ``replica_id`` must be unique within a fleet —
    the router keys stream bindings by ``(replica_id, rid)`` so a
    failed-over request's stale events can never leak into its
    stream.
    """

    def __init__(self, replica_id: str, engine: InferenceEngine, *,
                 watchdog_s: float = 0.0):
        self.id = replica_id
        self.engine = engine
        self.alive = True
        self.draining = False
        self.watchdog = None
        if watchdog_s:
            from ray_tpu.resilience.watchdog import EngineWatchdog
            # NOT .start()ed: the router's poll loop probes check()
            self.watchdog = EngineWatchdog(engine, timeout_s=watchdog_s)
        # test/chaos hook: a "wedged" replica has work but its step
        # stops ticking (the engine stamp freezes -> the watchdog
        # fires); real wedges are a hung device step, which host-sim
        # cannot produce in a single-threaded drive loop
        self._stalled = False
        self.reaped = False
        # prefix-digest memo, keyed by engine tick: registrations only
        # happen inside step() (which bumps ticks), so within one
        # router poll the digest is immutable — the routing hot path
        # must not rebuild an O(pages) frozenset per candidate per
        # request.  (A set_params prefix flush without a tick can
        # serve one stale digest: a routing-quality blip, never a
        # correctness one — admission re-walks the real index.)
        self._digest: Optional[frozenset] = None
        self._digest_ticks = -1

    # --------------------------------------------------------- admission
    def submit(self, prompt, *, max_new_tokens: int, sampling=None,
               eos_token=None, ttft_deadline_s=None,
               deadline_s=None) -> int:
        """Admit one request; raises the typed re-route signals
        (``ReplicaDrainingError`` / ``QueueFullError``) the router
        retries on, or ``ValueError`` for a request this fleet's
        geometry can never serve (the router fails the stream)."""
        if not self.alive:
            raise RuntimeError(f"replica {self.id} is dead — the "
                               "router must not route to it")
        if self.draining:
            from ray_tpu.inference.serve_gpt import ReplicaDrainingError
            raise ReplicaDrainingError(
                f"replica {self.id} is draining: admission stopped, "
                "in-flight requests finishing — route elsewhere")
        return self.engine.submit(prompt, max_new_tokens=max_new_tokens,
                                  sampling=sampling, eos_token=eos_token,
                                  ttft_deadline_s=ttft_deadline_s,
                                  deadline_s=deadline_s)

    # -------------------------------------------------------------- tick
    def step(self) -> List[StepEvent]:
        """One engine tick.  The ``serve.replica`` fault site fires
        BEFORE the engine steps (donated buffers untouched, scheduler
        consistent) and any raise — injected or real — marks the
        replica dead before propagating, so the router's failover path
        sees a consistent corpse."""
        from ray_tpu.util import chaos
        if self._stalled:
            return []                  # wedge: work pending, no tick
        try:
            chaos.maybe_fail("serve.replica")
            return self.engine.step()
        except BaseException:
            self.alive = False
            raise

    # ------------------------------------------------------------ health
    @property
    def wedged(self) -> bool:
        return self.watchdog is not None and self.watchdog.wedges > 0

    @property
    def wedges(self) -> int:
        return self.watchdog.wedges if self.watchdog is not None else 0

    def check(self, now: Optional[float] = None) -> None:
        """Probe the watchdog (the router calls this each poll)."""
        if self.watchdog is not None:
            self.watchdog.check(now)

    def stall(self) -> None:
        """Wedge this replica (test/driver hook): work stops ticking,
        the engine stamps freeze, and the next watchdog probe past the
        budget declares the wedge."""
        self._stalled = True

    def has_work(self) -> bool:
        return self.alive and self.engine.has_work()

    def queue_depth(self) -> int:
        """Waiting + active — the pow-2 load signal."""
        sched = self.engine.scheduler
        return len(sched.waiting) + len(sched.active)

    def waiting_depth(self) -> int:
        return len(self.engine.scheduler.waiting)

    def prefix_digest(self) -> frozenset:
        ticks = self.engine.ticks
        if self._digest is None or self._digest_ticks != ticks:
            self._digest = self.engine.prefix_digest()
            self._digest_ticks = ticks
        return self._digest

    # ------------------------------------------------------------- drain
    def drain(self) -> None:
        self.draining = True

    @property
    def drained(self) -> bool:
        return self.draining and not self.engine.has_work()

    # -------------------------------------------------------------- reap
    def reap(self) -> int:
        """Host-side teardown for a dead/wedged replica being replaced:
        retire every request so slots/pages/prefix refcounts release
        (the r15 dead-actor precedent — the corpse must audit clean
        before it is dropped).  Returns retired-request count."""
        self.reaped = True
        return self.engine.drain_requests()

    def leak_free(self) -> bool:
        """Fleet-wide leak audit: every slot free, every page either
        free or parked idle in the prefix pool, nothing in flight."""
        sched = self.engine.scheduler
        return (not sched.active and not sched.waiting
                and len(sched.free_slots) == self.engine.slots
                and sched.allocator.free_count
                == sched.allocator.num_pages - 1)

    def stats(self) -> Dict[str, Any]:
        out = self.engine.stats()
        out["replica"] = self.id
        out["alive"] = self.alive
        out["draining"] = self.draining
        out["wedges"] = self.wedges
        out["last_wedge_ts"] = (self.watchdog.last_wedge_ts
                                if self.watchdog is not None else None)
        return out
