"""Fleet-layer env knobs — the single home for router/reconciler config.

Follows the ``infer_config()`` / ``rl_config()`` precedent: one frozen
dataclass resolved from the environment once, ``refresh=True`` for
tests and A/B drivers that flip flags after import.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet router/reconciler knobs, resolved once from the environment.

    - ``RAY_TPU_FLEET_RETRIES`` (default ``2``): mid-stream failover
      budget per request — how many times a stream may be re-admitted
      on a healthy replica after its replica died or wedged before the
      router gives up with a typed
      :class:`~ray_tpu.fleet.router.ReplicaUnavailableError`.
      Draining/queue-full rejections are immediate re-route signals
      and do **not** consume this budget (each replica is tried at
      most once per routing attempt, so re-routing always terminates).
    - ``RAY_TPU_FLEET_AFFINITY`` (default ``1``): prefix-affinity
      routing — prompts whose chained page hashes hit a replica's
      prefix index route to that replica (the r12 cache working
      fleet-wide); ``0`` falls back to pure power-of-two-choices.
    - ``RAY_TPU_FLEET_AFFINITY_CAP`` (default ``8``): queue-depth cap
      above which an affinity hit is overridden — a hot replica must
      not absorb every shared-prefix request while its neighbours sit
      idle (the arXiv:2011.03641 saturated-not-overloaded argument).
    - ``RAY_TPU_FLEET_ADAPTER_AFFINITY`` (default ``1``): adapter-
      residency affinity (r25 multi-tenant serving) — a request whose
      ``model_id`` is already resident in a replica's LoRA bank scores
      toward that replica (skipping the store fetch + bank install a
      cold replica would pay), composing with the prefix-affinity
      score above; ``0`` is the residency-blind A/B arm
      (``bench.py --infer --lora`` measures the delta).
    - ``RAY_TPU_FLEET_UP_DEPTH`` (default ``4``): mean waiting-queue
      depth per running replica that, sustained for the dwell, scales
      the fleet up.
    - ``RAY_TPU_FLEET_TTFT_SLO`` (default ``0`` = off): TTFT SLO in
      seconds — recent first-token latencies above this, sustained
      for the dwell, also scale up (queue depth can look fine while
      TTFT burns on slow prefills).
    - ``RAY_TPU_FLEET_DWELL`` (default ``5``): anti-flap hysteresis in
      seconds — the minimum time a scale signal must persist before
      the reconciler acts, and the minimum dwell in a state before a
      voluntary transition (failure transitions are immediate).
    - ``RAY_TPU_FLEET_BACKOFF`` (default ``0.5``) /
      ``RAY_TPU_FLEET_BACKOFF_MAX`` (default ``30``): restart backoff
      — a wedged/dead replica restarts after
      ``min(backoff * 2**restarts, backoff_max)`` seconds, so a
      crash-looping replica cannot hot-loop the factory.
    - ``RAY_TPU_FLEET_SLOW_FACTOR`` (default ``3``, ``0`` = off): the
      gray-failure demotion threshold — a replica whose EWMA tick
      latency exceeds this multiple of the fleet median is excluded
      from routing (soft demotion: when *every* replica is slow the
      router still routes, a demotion must never be a dead-end) and
      reported to the reconciler as DEGRADED.
    - ``RAY_TPU_FLEET_HEDGE`` (default ``1``): tail-latency hedging —
      a stream whose first token has not arrived by the hedge deadline
      is re-admitted on a second replica; the first responder wins and
      the loser is cancelled (at-most-once delivery is structural:
      stream bindings are keyed ``(replica_id, rid)`` and the losing
      binding drops before its token could land).
    - ``RAY_TPU_FLEET_HEDGE_FACTOR`` (default ``2``): hedge deadline
      as a multiple of the router's rolling p99 TTFT — informed by
      observed tails, so healthy traffic almost never hedges.
    - ``RAY_TPU_FLEET_HEDGE_MIN`` (default ``0.05``): hedge-deadline
      floor in seconds (and the whole deadline until enough TTFT
      samples exist) — a cold fleet must not hedge every request.
    - ``RAY_TPU_FLEET_DISAGG`` (default ``0``): serve in disaggregated
      prefill/decode mode — ``bench.py --infer`` (and drivers reading
      this config) split the fleet into a prefill pool and a decode
      pool behind the :class:`~ray_tpu.fleet.disagg.DisaggRouter`
      instead of N co-located replicas.
    - ``RAY_TPU_FLEET_PREFILL_REPLICAS`` (default ``1``): how many of
      a disaggregated fleet's replicas form the prefill pool (the rest
      decode) — prefill is compute-bound and batches well, so one
      prefill replica typically feeds several decode replicas.
    - ``RAY_TPU_FLEET_HANDOFF_INLINE`` (default ``0``): force KV
      handoffs to bypass the object store and pass the payload
      in-process (``1``); by default the payload rides ``ray_tpu.put``
      whenever a session is up (the r14 ``WeightStore`` shape) and
      falls back inline otherwise.
    """
    retries: int = 2
    affinity: bool = True
    affinity_cap: int = 8
    adapter_affinity: bool = True
    up_depth: float = 4.0
    ttft_slo: float = 0.0
    dwell: float = 5.0
    backoff: float = 0.5
    backoff_max: float = 30.0
    slow_factor: float = 3.0
    hedge: bool = True
    hedge_factor: float = 2.0
    hedge_min: float = 0.05
    disagg: bool = False
    prefill_replicas: int = 1
    handoff_inline: bool = False


_CONFIG: Optional[FleetConfig] = None


def fleet_config(refresh: bool = False) -> FleetConfig:
    """The process-wide :class:`FleetConfig` (env read once, cached)."""
    global _CONFIG
    if _CONFIG is None or refresh:
        env = os.environ.get

        def nonneg(name, default, cast=float):
            val = cast(env(name, default))
            if val < 0:
                print(f"{name}={val} negative; using {default}",
                      file=sys.stderr)
                return cast(default)
            return val

        _CONFIG = FleetConfig(
            retries=nonneg("RAY_TPU_FLEET_RETRIES", "2", int),
            affinity=env("RAY_TPU_FLEET_AFFINITY", "1") != "0",
            affinity_cap=nonneg("RAY_TPU_FLEET_AFFINITY_CAP", "8", int),
            adapter_affinity=env("RAY_TPU_FLEET_ADAPTER_AFFINITY",
                                 "1") != "0",
            up_depth=nonneg("RAY_TPU_FLEET_UP_DEPTH", "4"),
            ttft_slo=nonneg("RAY_TPU_FLEET_TTFT_SLO", "0"),
            dwell=nonneg("RAY_TPU_FLEET_DWELL", "5"),
            backoff=nonneg("RAY_TPU_FLEET_BACKOFF", "0.5"),
            backoff_max=nonneg("RAY_TPU_FLEET_BACKOFF_MAX", "30"),
            slow_factor=nonneg("RAY_TPU_FLEET_SLOW_FACTOR", "3"),
            hedge=env("RAY_TPU_FLEET_HEDGE", "1") != "0",
            hedge_factor=nonneg("RAY_TPU_FLEET_HEDGE_FACTOR", "2"),
            hedge_min=nonneg("RAY_TPU_FLEET_HEDGE_MIN", "0.05"),
            disagg=env("RAY_TPU_FLEET_DISAGG", "0") != "0",
            prefill_replicas=max(
                nonneg("RAY_TPU_FLEET_PREFILL_REPLICAS", "1", int), 1),
            handoff_inline=env("RAY_TPU_FLEET_HANDOFF_INLINE",
                               "0") != "0",
        )
    return _CONFIG
