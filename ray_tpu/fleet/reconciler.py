"""Autoscaler-v2-style reconciler: an instance state machine over the
fleet.

Parity: the reference's ``autoscaler/v2/instance_manager/reconciler.py``
— desired state (a target replica count plus scale signals) is
reconciled against observed instance state on every
:meth:`Reconciler.reconcile` call, and every decision is a pure
function of ``(instances, signals, now)`` so a test can drive the
whole machine with an explicit clock.

States::

    STARTING -> RUNNING -> DRAINING -> STOPPED
                   \\-> WEDGED -> RESTARTING -> RUNNING
                   \\<-> DEGRADED -> DRAINING -> STOPPED (+replacement)

- **WEDGED requires a health signal**: a replica only leaves RUNNING
  for WEDGED when it is dead (``alive`` False) or its r15 watchdog
  wedge counter moved — a slow-but-ticking replica never restarts
  *immediately*.
- **DEGRADED is the gray-failure arm** (r19): the router's latency
  demotion signal (EWMA tick latency past
  ``RAY_TPU_FLEET_SLOW_FACTOR``x the fleet median) moves a RUNNING
  replica to DEGRADED.  A blip recovers to RUNNING; a demotion
  sustained for the dwell triggers a **drain-restart**: the replica
  drains (admission stops, in-flight streams finish — zero dropped)
  while target restoration spawns its replacement, and the corpse
  retires once drained.  A chronically slow replica is thus recycled
  without ever being trusted to finish nothing.
- **RESTARTING** replaces the corpse through the factory; replacement
  engines share the fleet's executable cache, so a restart costs
  construction, not XLA (the zero-steady-state-recompiles acceptance
  counter).  Restart backoff doubles per restart and is capped
  (``RAY_TPU_FLEET_BACKOFF``/``_MAX``) — a crash-looping replica
  cannot hot-loop the factory.
- **Scale up** on sustained queue-depth pressure or TTFT-SLO breach
  (``RAY_TPU_FLEET_UP_DEPTH`` / ``RAY_TPU_FLEET_TTFT_SLO``), **scale
  down** through ``drain()`` only — a DRAINING replica stops admitting
  (the router re-routes) but finishes every in-flight stream before it
  STOPs, so scale-down drops zero streams (the router refuses to
  remove a replica with bound streams).
- **Anti-flap hysteresis**: a scale signal must persist for
  ``RAY_TPU_FLEET_DWELL`` before acting, and consecutive scale actions
  are at least a dwell apart.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.fleet.config import FleetConfig, fleet_config

STARTING = "STARTING"
RUNNING = "RUNNING"
DRAINING = "DRAINING"
STOPPED = "STOPPED"
WEDGED = "WEDGED"
RESTARTING = "RESTARTING"
DEGRADED = "DEGRADED"


@dataclasses.dataclass
class Instance:
    """Observed + desired state for one replica slot."""
    replica: Any
    state: str
    since: float
    restarts: int = 0
    wedges_seen: int = 0
    restart_at: float = 0.0      # backoff gate while WEDGED
    degraded_since: float = 0.0  # dwell gate while DEGRADED


class Reconciler:
    """Reconcile the fleet toward ``target`` healthy replicas.

    ``factory(replica_id)`` builds a replacement/scale-up replica
    (sharing the executable cache is the factory's job); ``target`` is
    the steady count restored after deaths and the scale-down floor;
    ``max_replicas`` (default ``target``) bounds scale-up.
    """

    def __init__(self, router, factory: Callable[[str], Any], *,
                 target: int, max_replicas: Optional[int] = None,
                 cfg: Optional[FleetConfig] = None,
                 now: Optional[float] = None):
        if target < 1:
            raise ValueError(f"target must be >= 1, got {target}")
        self.router = router
        self.factory = factory
        self.target = target
        self.max_replicas = max(max_replicas or target, target)
        self.cfg = cfg or fleet_config()
        now = time.monotonic() if now is None else now
        self.instances: Dict[str, Instance] = {
            r.id: Instance(replica=r, state=RUNNING, since=now)
            for r in router.replicas()}
        self._spawned = 0
        self.restarts_total = 0
        self.demotion_restarts = 0   # gray-failure drain-restarts
        # r23: spawns whose engine came up attached to a non-empty
        # fleet-shared KV page store — a restart or scale-from-zero
        # replica that warms up from other replicas' prefix pages on
        # its first admissions instead of re-prefilling everything
        self.warm_starts = 0
        self._breach_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_scale_ts = now

    # ------------------------------------------------------------- views
    def states(self) -> Dict[str, str]:
        return {rid: inst.state for rid, inst in self.instances.items()}

    def _count(self, *states: str) -> int:
        return sum(1 for i in self.instances.values()
                   if i.state in states)

    def _backoff(self, restarts: int) -> float:
        return min(self.cfg.backoff * (2 ** restarts),
                   self.cfg.backoff_max)

    def _new_id(self) -> str:
        self._spawned += 1
        return f"r{len(self.instances)}-{self._spawned}"

    def _spawn(self, now: float, *, state: str = STARTING,
               restarts: int = 0) -> Instance:
        rid = self._new_id()
        replica = self.factory(rid)
        self.router.add_replica(replica)
        store = getattr(getattr(replica, "engine", None), "store", None)
        if store is not None and len(store):
            self.warm_starts += 1
        inst = Instance(replica=replica, state=state, since=now,
                        restarts=restarts)
        self.instances[rid] = inst
        return inst

    # --------------------------------------------------------- reconcile
    def reconcile(self, now: Optional[float] = None) -> List[str]:
        """One reconciliation pass; returns the actions taken (state
        transitions and scale decisions) for logs and tests."""
        now = time.monotonic() if now is None else now
        actions: List[str] = []
        # the router's instantaneous latency verdict; the dwell below
        # converts it into a decision (blip vs chronic)
        slow = self.router.slow_replicas() \
            if hasattr(self.router, "slow_replicas") else set()

        def move(rid, inst, state):
            actions.append(f"{rid}: {inst.state}->{state}")
            inst.state = state
            inst.since = now

        for rid, inst in list(self.instances.items()):
            r = inst.replica
            if inst.state in (STARTING, RESTARTING):
                # in-process replicas are ready at construction; the
                # distinct state exists so a pass can observe the spawn
                move(rid, inst, RUNNING)
            if inst.state == RUNNING:
                wedge_signal = (not r.alive
                                or r.wedges > inst.wedges_seen)
                if wedge_signal:
                    inst.wedges_seen = r.wedges
                    inst.restart_at = now + self._backoff(inst.restarts)
                    move(rid, inst, WEDGED)
                elif rid in slow:
                    inst.degraded_since = now
                    move(rid, inst, DEGRADED)
            if inst.state == DEGRADED:
                # gray turned black: death/wedge dominates slowness
                if not r.alive or r.wedges > inst.wedges_seen:
                    inst.wedges_seen = r.wedges
                    inst.restart_at = now + self._backoff(inst.restarts)
                    move(rid, inst, WEDGED)
                elif rid not in slow:
                    # a blip: the score recovered before the dwell —
                    # re-promoted, nothing recycled
                    move(rid, inst, RUNNING)
                elif now - inst.degraded_since >= self.cfg.dwell:
                    # chronically slow: drain-restart.  Admission
                    # stops (the router re-routes), in-flight streams
                    # finish (zero dropped), target restoration below
                    # spawns the replacement this same pass, and the
                    # DRAINING branch retires the corpse once drained.
                    r.drain()
                    self.demotion_restarts += 1
                    self.router.telemetry.record_restart()
                    move(rid, inst, DRAINING)
                    actions[-1] += " (degraded drain-restart)"
            if inst.state == WEDGED and now >= inst.restart_at:
                # replace the corpse: reap (slots/pages/refcounts
                # release so the fleet audit stays clean), drop from
                # routing, spawn the replacement with escalated backoff
                r.alive = False       # a wedged survivor must not serve
                if not r.reaped:
                    r.reap()
                self.router.remove_replica(rid)
                move(rid, inst, STOPPED)
                del self.instances[rid]
                new = self._spawn(now, state=RESTARTING,
                                  restarts=inst.restarts + 1)
                self.restarts_total += 1
                self.router.telemetry.record_restart()
                actions.append(f"{new.replica.id}: RESTARTING "
                               f"(for {rid}, restart "
                               f"#{inst.restarts + 1})")
            if inst.state == DRAINING:
                # health checks apply while draining too — a replica
                # that dies or wedges mid-drain would otherwise be a
                # permanent zombie (its cancels never process, so
                # `drained` never turns true).  It was leaving anyway:
                # reap (slots/pages/refcounts release), no replacement.
                if not r.alive or r.wedges > inst.wedges_seen:
                    inst.wedges_seen = r.wedges
                    r.alive = False
                    if not r.reaped:
                        r.reap()
                if (r.drained or not r.alive) \
                        and self.router.bound_streams(rid) == 0:
                    # (bound streams from a mid-drain death are failed
                    # over by the router's next poll; retire then)
                    self.router.remove_replica(rid)
                    move(rid, inst, STOPPED)
                    del self.instances[rid]

        self._reconcile_scale(now, actions)
        return actions

    # ----------------------------------------------------------- scaling
    def _signals(self) -> Dict[str, float]:
        running = [i.replica for i in self.instances.values()
                   if i.state == RUNNING and i.replica.alive]
        waiting = sum(r.waiting_depth() for r in running)
        depth = sum(r.queue_depth() for r in running)
        ttfts = self.router.recent_ttfts()
        return {
            "running": len(running),
            "mean_waiting": waiting / len(running) if running else 0.0,
            "total_depth": depth,
            "ttft_p50": statistics.median(ttfts) if ttfts else 0.0,
        }

    def _reconcile_scale(self, now: float, actions: List[str]) -> None:
        sig = self._signals()
        # WEDGED counts as live: its 1:1 replacement is already
        # scheduled behind the backoff gate — spawning a restore on
        # top would overshoot the target by one per wedge.  DEGRADED
        # counts too (it still serves); only its drain-restart drops
        # it from this set, which is exactly what lets restoration
        # spawn the replacement.
        live = self._count(STARTING, RUNNING, RESTARTING, WEDGED,
                           DEGRADED)

        # target restoration is failure recovery, not autoscaling: no
        # dwell gate — a killed replica's capacity comes back now
        while live < self.target:
            inst = self._spawn(now)
            actions.append(f"{inst.replica.id}: STARTING (restore "
                           f"target {self.target})")
            live += 1

        breach = sig["mean_waiting"] >= self.cfg.up_depth or (
            self.cfg.ttft_slo > 0
            and sig["ttft_p50"] > self.cfg.ttft_slo)
        if breach:
            self._idle_since = None
            if self._breach_since is None:
                self._breach_since = now
            elif (now - self._breach_since >= self.cfg.dwell
                    and now - self._last_scale_ts >= self.cfg.dwell
                    and live < self.max_replicas):
                inst = self._spawn(now)
                self._last_scale_ts = now
                self._breach_since = None
                actions.append(f"{inst.replica.id}: STARTING "
                               f"(scale-up: mean_waiting="
                               f"{sig['mean_waiting']:.1f}, ttft_p50="
                               f"{sig['ttft_p50']:.3f}s)")
            return
        self._breach_since = None

        idle = sig["total_depth"] == 0
        if idle and sig["running"] > self.target:
            if self._idle_since is None:
                self._idle_since = now
            elif (now - self._idle_since >= self.cfg.dwell
                    and now - self._last_scale_ts >= self.cfg.dwell):
                # newest RUNNING instance drains first (LIFO: the
                # scale-up surge unwinds in reverse)
                rid, inst = max(
                    ((rid, i) for rid, i in self.instances.items()
                     if i.state == RUNNING and i.replica.alive),
                    key=lambda kv: kv[1].since)
                inst.replica.drain()
                actions.append(f"{rid}: RUNNING->DRAINING (scale-down)")
                inst.state = DRAINING
                inst.since = now
                self._last_scale_ts = now
                self._idle_since = None
        else:
            self._idle_since = None
