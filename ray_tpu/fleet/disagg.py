"""Disaggregated prefill/decode serving: split replica pools with
KV-page handoff through the object store.

Prefill batches are compute-bound while decode is latency-bound (the
arXiv:2011.03641 concurrency-limits argument), so co-locating them on
one replica forces every decode tick to queue behind someone else's
prefill — exactly the interference the r19 gray-failure work had to
hedge around.  This module splits them: a **prefill pool** of replicas
whose streams end at the first sampled token (``max_new_tokens=1``
first-token-stop submissions with ``hold_pages=True``), and a **decode
pool** that imports the handed-off KV pages into its own allocator,
seeds the slot at the absolute context offset, and streams the rest
through the one compiled decode executable.

**The handoff is a transfer of page ownership, not a copy protocol.**
Pages are already content-addressed (r12 chained hashes) and
refcounted, so the payload
(:class:`~ray_tpu.inference.kv_cache.KVHandoff`) is the cached
context's tokens + chained page hashes + raw K/V contents — int8 codes
and scales ride the same arrays, halving the bytes vs bf16 — and moves
through the object store (``ray_tpu.put``-shaped, the r14
``WeightStore`` precedent; :class:`HandoffStore`).  The import installs
through the existing ``PrefixIndex`` registration: a decode replica
that already holds the prefix by content hash acquires refcounts and
skips the content writes, and when it holds *every* context page the
router ships metadata only — **affinity routing by page digest makes
warm handoffs near-free** (the decode-side pick mirrors the r16
prefix-affinity pick, keyed by the handoff's chain hashes).

**Failure semantics stay as strong as r16/r19.**  A prefill replica
dying after export, a decode replica dying after import, or a
``serve.handoff`` chaos fault on either leg of the transfer all degrade
to the same re-prefill-from-prompt failover the co-located fleet uses:
the stream re-admits on the prefill pool with ``prompt + every token
already emitted`` (at-most-once delivery is structural — the stream
asserts over-delivery) and hands off again.  Orphaned exports cannot
leak: held pages are released on every failure path, a reaped corpse's
``drain_requests`` covers them, and :meth:`DisaggRouter.leak_free`
additionally audits in-flight handoff objects in the store.

**Zero steady-state recompiles hold on both pools**: the prefill pool
runs the r10/r12 prefill executables, and the decode pool's "suffix of
length 1 over imported context" is just the ordinary fixed-slot decode
step over a seeded slot — imports compile *nothing* (the acceptance
test asserts the counters).

**Autoscaling** stays the r16 reconciler, one per pool through
:meth:`DisaggRouter.pool_view`: the prefill pool scales on queue depth
and TTFT (its TTFTs are the fleet's TTFTs — the first token comes from
prefill), the decode pool on slot occupancy (a queued import means
every decode slot is busy — ``waiting_depth`` IS the occupancy
backlog).

Knobs: ``RAY_TPU_FLEET_DISAGG`` / ``RAY_TPU_FLEET_PREFILL_REPLICAS`` /
``RAY_TPU_FLEET_HANDOFF_INLINE`` (:func:`~ray_tpu.fleet.config.
fleet_config`), plus the shared ``RAY_TPU_FLEET_*`` routing knobs.
"""

from __future__ import annotations

import collections
import random
import statistics
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.fleet.config import FleetConfig, fleet_config
from ray_tpu.fleet.replica import EngineReplica
from ray_tpu.fleet.router import ReplicaUnavailableError
from ray_tpu.inference.kv_cache import (HandoffContentMissing, KVHandoff,
                                        PrefixIndex)
from ray_tpu.inference.scheduler import QueueFullError
from ray_tpu.telemetry import trace as trace_mod

PREFILL = "prefill"
DECODE = "decode"


class HandoffStore:
    """``ray_tpu.put``-shaped home for in-flight handoff payloads.

    Mirrors the r14 ``WeightStore`` split: payloads ride the real
    object store when a session is up and an in-process slot otherwise
    — ``RAY_TPU_FLEET_HANDOFF_INLINE=1`` forces the inline path either
    way.  The router materializes the payload itself before
    ``submit_import`` because every replica is host-driven in this
    process (the r16 architecture); with a session up the put/get pair
    prices the serialize/transit cost honestly, and handing the raw
    ref to a genuinely remote decode replica — fetch on the importer,
    no driver round trip — is the multi-host follow-up.  Every live
    handle is tracked so the fleet-wide leak audit can assert none is
    orphaned (``in_flight``), and byte counters feed the
    ``serve_handoff_bytes_total`` telemetry."""

    def __init__(self, use_object_store: Optional[bool] = None, *,
                 cfg: Optional[FleetConfig] = None):
        if use_object_store is None:
            cfg = cfg or fleet_config()
            if cfg.handoff_inline:
                use_object_store = False
            else:
                from ray_tpu._private.worker import is_initialized
                use_object_store = is_initialized()
        self._use_ray = bool(use_object_store)
        self._live: Dict[int, Any] = {}     # handle id -> payload/ref
        self._next = 0
        self.puts = 0
        self.bytes_put = 0

    @property
    def in_flight(self) -> int:
        return len(self._live)

    def put(self, payload: KVHandoff) -> int:
        """Stash one payload; returns its handle (drop it when the
        import lands or the handoff is abandoned)."""
        obj: Any = payload
        if self._use_ray:
            import ray_tpu
            obj = ray_tpu.put(payload)
        handle = self._next
        self._next += 1
        self._live[handle] = obj
        self.puts += 1
        self.bytes_put += payload.nbytes
        return handle

    def get(self, handle: int) -> KVHandoff:
        obj = self._live[handle]
        if self._use_ray:
            import ray_tpu
            return ray_tpu.get(obj)
        return obj

    def drop(self, handle: int) -> None:
        """Release a handle (idempotent): the payload's pages-worth of
        store memory frees — the refcount half of 'orphaned exported
        pages cannot leak'."""
        self._live.pop(handle, None)


class DisaggStream:
    """One disaggregated request: iterate tokens as they land (the
    :class:`~ray_tpu.fleet.router.FleetStream` shape — bare token ids,
    or ``{"token", "logprob"}`` dicts under ``{"logprobs": True}``).
    The stream's life is prefill → handoff → decode; failovers restart
    it at prefill with the emitted tokens carried forward."""

    def __init__(self, router: "DisaggRouter", payload: Dict[str, Any]):
        from ray_tpu.inference.serve_gpt import parse_request
        self._router = router
        self.prompt = [int(t) for t in payload["tokens"]]
        parsed = parse_request(payload)
        self.max_new_tokens = parsed["max_new_tokens"]
        self.sampling = parsed["sampling"]
        self.want_logprobs = parsed["want_logprobs"]
        self.eos_token = parsed["eos_token"]
        self.ttft_deadline_s = parsed["ttft_deadline_s"]
        self.deadline_s = parsed["deadline_s"]
        # r24: every disagg request owns one trace — the context rides
        # the prefill submit AND the handoff payload, so both replicas'
        # spans join a single tree under this root
        ctx = trace_mod.mint()
        root_id = trace_mod.record_span(
            "request", ctx, start=time.time(), dur=0.0,
            prompt_tokens=len(self.prompt),
            max_new=self.max_new_tokens, disagg=True)
        self.trace = ctx.child(root_id) if root_id is not None else ctx
        self.submitted_ts = time.monotonic()
        self.first_token_ts: Optional[float] = None
        self.generated: List[int] = []
        self.logprobs: List[float] = []
        self.token_ts: List[float] = []
        self._cursor = 0
        self.done = False
        self.error: Optional[BaseException] = None
        self.retries = 0
        self.handoffs = 0            # completed page handoffs
        self.phase: Optional[str] = None          # PREFILL | DECODE
        self.replica_id: Optional[str] = None
        self.rid: Optional[int] = None

    # ------------------------------------------------- router callbacks
    def _push(self, token: int, logprob: float) -> None:
        if len(self.generated) >= self.max_new_tokens:
            raise AssertionError(
                f"stream got token {len(self.generated) + 1} of "
                f"{self.max_new_tokens}: duplicate delivery after "
                "failover")
        now = time.monotonic()
        if self.first_token_ts is None:
            self.first_token_ts = now
            ttft = now - self.submitted_ts
            self._router._record_ttft(ttft,
                                      trace_id=self.trace.trace_id)
            trace_mod.event("first_token", self.trace, ttft_s=ttft,
                            replica=self.replica_id)
        self.generated.append(int(token))
        self.logprobs.append(float(logprob))
        self.token_ts.append(now)

    def _finish(self) -> None:
        self.done = True
        trace_mod.event("request_end", self.trace,
                        tokens=len(self.generated),
                        handoffs=self.handoffs)

    def _fail(self, err: BaseException) -> None:
        self.error = err
        self.done = True
        trace_mod.event("request_error", self.trace,
                        error=type(err).__name__)

    @property
    def complete(self) -> bool:
        """Every requested token emitted (or EOS hit) — nothing left
        to hand off or decode."""
        return (len(self.generated) >= self.max_new_tokens
                or (self.eos_token is not None and self.generated
                    and self.generated[-1] == self.eos_token))

    # ---------------------------------------------------------- consume
    def __iter__(self):
        return self

    def __next__(self):
        while self._cursor >= len(self.generated):
            if self.error is not None:
                raise self.error
            if self.done:
                raise StopIteration
            if not self._router.poll():
                time.sleep(0.001)
        tok = self.generated[self._cursor]
        lp = self.logprobs[self._cursor]
        self._cursor += 1
        return {"token": tok, "logprob": lp} if self.want_logprobs \
            else tok

    def result(self) -> List[int]:
        for _ in self:
            pass
        return list(self.generated)

    def close(self) -> None:
        """Abandon the stream: cancel whichever pool currently holds
        it so its slot/pages/prefix refs free within a tick."""
        self._router._cancel_stream(self)


class PoolView:
    """Reconciler-protocol adapter over one pool of a
    :class:`DisaggRouter` — the r16 :class:`~ray_tpu.fleet.reconciler.
    Reconciler` drives each pool through one of these, unchanged: the
    prefill view surfaces the fleet TTFTs (queue-depth/TTFT-SLO
    scale-up), the decode view surfaces none (its ``mean_waiting``
    signal is queued imports = slot occupancy backlog)."""

    def __init__(self, router: "DisaggRouter", pool: str):
        self._router = router
        self.pool = pool

    def replicas(self) -> List[EngineReplica]:
        return list(self._router._pools[self.pool].values())

    def add_replica(self, replica: EngineReplica) -> None:
        self._router.add_replica(replica, pool=self.pool)

    def remove_replica(self, replica_id: str) -> EngineReplica:
        pool = self._router._pool_of.get(replica_id)
        if pool != self.pool:
            # the adapter's whole point is the pool boundary: a
            # reconciler must not silently shrink the OTHER pool
            raise ValueError(
                f"replica {replica_id!r} is in pool {pool!r}, not "
                f"this view's {self.pool!r}")
        return self._router.remove_replica(replica_id)

    def bound_streams(self, replica_id: str) -> int:
        return self._router.bound_streams(replica_id)

    def slow_replicas(self) -> set:
        return self._router.slow_replicas(self.pool)

    def recent_ttfts(self) -> List[float]:
        return (self._router.recent_ttfts() if self.pool == PREFILL
                else [])

    @property
    def telemetry(self):
        return self._router.telemetry


class DisaggRouter:
    """Front a prefill pool and a decode pool as one service.

    Host-driven like the r16 :class:`~ray_tpu.fleet.router.FleetRouter`
    (the router owns the tick loop and steps every replica itself), so
    every routing, handoff and recovery decision is deterministic under
    a ``RAY_TPU_FAULTS`` plan.  The pick/health helpers
    (`_update_health`/`_effective_load`/`_affinity_pick`/`_pow2_pick`)
    deliberately mirror ``router.py``'s — per-pool medians and a
    two-pool binding model don't graft cleanly onto the hedging-aware
    FleetRouter, so a behavioral fix to either copy should be applied
    to both (they are kept line-comparable on purpose).  All replicas
    — both pools — must share
    page size, bucket geometry and KV dtype: the handoff payload is
    raw page contents, and failover re-admission assumes any prefill
    replica accepts the same prompt lengths.

    Per request: route to a prefill replica (prefix-affinity by the
    prompt's chained page hashes, else pow-2 on queue depth), collect
    its first token (``max_new_tokens=1`` + ``hold_pages``), then hand
    the KV pages to a decode replica picked by *digest affinity over
    the handoff's chain hashes* — the replica already holding the most
    context pages wins, and one holding **all** of them gets a
    metadata-only handoff with zero content bytes.  The decode replica
    imports, seeds the slot at the absolute offset, and the stream
    rides ordinary batched decode to completion.
    """

    _TTFT_WINDOW = 256

    def __init__(self, prefill: List[EngineReplica],
                 decode: List[EngineReplica], *,
                 cfg: Optional[FleetConfig] = None,
                 affinity: Optional[bool] = None,
                 store: Optional[HandoffStore] = None,
                 rng_seed: int = 0, telemetry=None):
        if not prefill or not decode:
            raise ValueError("a disaggregated fleet needs >= 1 replica "
                             "in BOTH pools (prefill and decode)")
        self.cfg = cfg or fleet_config()
        self.affinity = (self.cfg.affinity if affinity is None
                         else bool(affinity))
        self._rng = random.Random(rng_seed)
        self._pools: Dict[str, "collections.OrderedDict[str, EngineReplica]"] = {
            PREFILL: collections.OrderedDict(),
            DECODE: collections.OrderedDict()}
        self._pool_of: Dict[str, str] = {}
        self._by_rid: Dict[Tuple[str, int], DisaggStream] = {}
        self._ttfts: "collections.deque[float]" = collections.deque(
            maxlen=self._TTFT_WINDOW)
        self._demoted: Dict[str, set] = {PREFILL: set(), DECODE: set()}
        self._median_latency: Dict[str, float] = {PREFILL: 0.0,
                                                  DECODE: 0.0}
        if telemetry is None:
            from ray_tpu.telemetry.fleet import FleetTelemetry
            telemetry = FleetTelemetry()
        self.telemetry = telemetry
        self._store = store if store is not None else \
            HandoffStore(cfg=self.cfg)
        ref = prefill[0].engine
        self.page_size = ref.page_size
        self.buckets = ref.buckets
        self.kv_dtype = ref.kv_dtype
        for r in prefill:
            self.add_replica(r, pool=PREFILL)
        for r in decode:
            self.add_replica(r, pool=DECODE)

    # ------------------------------------------------------------- fleet
    @property
    def store(self) -> HandoffStore:
        return self._store

    def add_replica(self, replica: EngineReplica, *, pool: str) -> None:
        if pool not in self._pools:
            raise ValueError(f"unknown pool {pool!r}; expected "
                             f"{PREFILL!r} or {DECODE!r}")
        if replica.id in self._pool_of:
            raise ValueError(f"duplicate replica id {replica.id!r} "
                             "(ids are fleet-unique across pools)")
        eng = replica.engine
        if (eng.page_size != self.page_size
                or eng.buckets != self.buckets
                or eng.kv_dtype != self.kv_dtype):
            raise ValueError(
                f"replica {replica.id!r} geometry (page_size "
                f"{eng.page_size}, buckets {eng.buckets}, kv_dtype "
                f"{eng.kv_dtype!r}) != fleet (page_size "
                f"{self.page_size}, buckets {self.buckets}, kv_dtype "
                f"{self.kv_dtype!r}) — handoffs move raw page "
                "contents, one fleet geometry")
        self._pools[pool][replica.id] = replica
        self._pool_of[replica.id] = pool

    def remove_replica(self, replica_id: str) -> EngineReplica:
        pool = self._pool_of.get(replica_id)
        if pool is None:
            raise KeyError(replica_id)
        bound = [k for k in self._by_rid if k[0] == replica_id]
        if bound:
            raise ValueError(
                f"replica {replica_id!r} still has {len(bound)} "
                "in-flight stream(s) — drain (or fail over) first")
        # removing a pool's last replica is legal (the reconciler
        # removes a corpse before spawning its replacement): routing
        # into a momentarily-empty pool surfaces the typed
        # ReplicaUnavailableError, never a hang
        del self._pool_of[replica_id]
        self.telemetry.forget_replica(replica_id)
        return self._pools[pool].pop(replica_id)

    def replicas(self, pool: Optional[str] = None) -> List[EngineReplica]:
        if pool is not None:
            return list(self._pools[pool].values())
        return [r for p in self._pools.values() for r in p.values()]

    def pool_view(self, pool: str) -> PoolView:
        if pool not in self._pools:
            raise ValueError(f"unknown pool {pool!r}")
        return PoolView(self, pool)

    def bound_streams(self, replica_id: str) -> int:
        return sum(1 for k in self._by_rid if k[0] == replica_id)

    def _healthy(self, pool: str) -> List[EngineReplica]:
        return [r for r in self._pools[pool].values()
                if r.alive and not r.draining and not r.wedged]

    # ---------------------------------------------------- health scoring
    def _update_health(self, pool: str) -> None:
        """Per-pool r19 latency demotion (the pools have different
        healthy tick profiles — a prefill tick is a whole bucket of
        compute, a decode tick one token — so the outlier median must
        be computed within the pool, never across it)."""
        factor = self.cfg.slow_factor
        newly: set = set()
        med = 0.0
        if factor > 0:
            scored = [(r.id, r.latency_score())
                      for r in self._healthy(pool)]
            scores = [s for _, s in scored if s > 0]
            if len(scores) >= 2:
                med = statistics.median_low(scores)
                if med > 0:
                    newly = {rid for rid, s in scored
                             if s > factor * med}
        for rid in sorted(newly - self._demoted[pool]):
            self.telemetry.record_demotion(rid)
            trace_mod.anomaly("demotion", replica=rid, pool=pool,
                              median_latency_s=med,
                              slow_factor=factor)
        self._demoted[pool] = newly
        self._median_latency[pool] = med

    def slow_replicas(self, pool: Optional[str] = None) -> set:
        if pool is not None:
            return set(self._demoted[pool])
        return self._demoted[PREFILL] | self._demoted[DECODE]

    def _effective_load(self, r: EngineReplica, pool: str) -> float:
        med = self._median_latency[pool]
        score = r.latency_score()
        rel = score / med if (med > 0 and score > 0) else 1.0
        return (r.queue_depth() + 1) * max(rel, 1.0)

    # ---------------------------------------------------------- routing
    def remote(self, payload: Dict[str, Any]) -> DisaggStream:
        """Route one request (the ``GPTDeployment`` payload dict);
        routing failures surface as the stream's typed error at first
        iteration, never an exception here (the streaming-path
        contract)."""
        stream = DisaggStream(self, payload)
        try:
            self._route_prefill(stream)
        except (ReplicaUnavailableError, ValueError) as e:
            stream._fail(e)
        return stream

    def _candidates(self, pool: str, excluded: set) -> List[EngineReplica]:
        cands = [r for r in self._healthy(pool)
                 if r.id not in excluded]
        fast = [r for r in cands if r.id not in self._demoted[pool]]
        return fast or cands        # soft demotion: never a dead-end

    def _affinity_pick(self, hashes: List[bytes], cands,
                       pool: str) -> Optional[EngineReplica]:
        """Longest-chain-hit pick (the r16 affinity rule, shared by
        both pools: prompt hashes against prefill caches, handoff
        hashes against decode caches)."""
        if not hashes:
            return None
        best, best_hits = None, 0
        for r in cands:
            digest = r.prefix_digest()
            hits = 0
            for h in hashes:
                if h not in digest:
                    break
                hits += 1
            if hits > best_hits:
                best, best_hits = r, hits
        if best is not None \
                and best.queue_depth() < self.cfg.affinity_cap:
            return best
        return None

    def _pow2_pick(self, cands, pool: str) -> EngineReplica:
        if len(cands) == 1:
            return cands[0]
        a, b = self._rng.sample(cands, 2)
        return a if (self._effective_load(a, pool)
                     <= self._effective_load(b, pool)) else b

    def _route_prefill(self, stream: DisaggStream) -> None:
        """(Re-)admit a stream on the prefill pool: a first-token-stop
        submission over ``prompt + every emitted token``.  Raises
        :class:`ReplicaUnavailableError` when no healthy prefill
        replica accepts."""
        from ray_tpu.inference.serve_gpt import ReplicaDrainingError
        from ray_tpu.util import chaos
        prompt = stream.prompt + stream.generated
        if len(prompt) > self.buckets[-1]:
            raise ReplicaUnavailableError(
                f"failover re-prefill needs {len(prompt)} prompt "
                f"tokens but the fleet's largest prefill bucket is "
                f"{self.buckets[-1]} — size RAY_TPU_INFER_BUCKETS to "
                "cover prompt + max_new_tokens for failover-proof "
                "requests", retries=stream.retries)
        hashes = PrefixIndex.chain_hashes(
            prompt, self.page_size)[:PrefixIndex.hit_eligible(
                len(prompt), self.page_size)] if self.affinity else []
        excluded: set = set()
        route_t0 = time.monotonic()
        rejected: List[str] = []
        while True:
            cands = self._candidates(PREFILL, excluded)
            if not cands:
                raise ReplicaUnavailableError(
                    f"no healthy prefill replica accepted the request "
                    f"({len(self._pools[PREFILL])} in the pool, "
                    f"{len(excluded)} rejected this attempt, "
                    f"{stream.retries} failover(s) used)",
                    retries=stream.retries)
            replica = None
            if self.affinity:
                replica = self._affinity_pick(hashes, cands, PREFILL)
                if not excluded and stream.retries == 0 \
                        and not stream.generated:
                    self.telemetry.record_affinity(
                        hit=replica is not None)
            if replica is None:
                replica = self._pow2_pick(cands, PREFILL)
            try:
                chaos.maybe_fail("serve.route")
                rid = replica.submit(
                    prompt, max_new_tokens=1, hold_pages=True,
                    sampling=stream.sampling,
                    eos_token=stream.eos_token,
                    # a re-admission's first token is NOT the stream's
                    # first token: 0 disables the engine-side TTFT
                    # deadline outright (None would re-arm the engine
                    # DEFAULT and could shed a stream whose real first
                    # token was delivered long ago)
                    ttft_deadline_s=(stream.ttft_deadline_s
                                     if not stream.generated else 0),
                    deadline_s=self._remaining_deadline(stream),
                    trace_ctx=stream.trace)
            except chaos.InjectedFault:
                self.telemetry.record_retry("dead")
                rejected.append(f"dead:{replica.id}")
                excluded.add(replica.id)
                continue
            except ReplicaDrainingError:
                self.telemetry.record_retry("draining")
                rejected.append(f"draining:{replica.id}")
                excluded.add(replica.id)
                continue
            except QueueFullError:
                self.telemetry.record_retry("queue_full")
                rejected.append(f"queue_full:{replica.id}")
                excluded.add(replica.id)
                continue
            stream.phase = PREFILL
            stream.replica_id, stream.rid = replica.id, rid
            self._by_rid[(replica.id, rid)] = stream
            if stream.trace.sampled:
                now = time.monotonic()
                trace_mod.record_span(
                    "route", stream.trace,
                    start=trace_mod.epoch_of(route_t0),
                    dur=now - route_t0, picked=replica.id,
                    pool=PREFILL, attempt=stream.retries,
                    rejected=rejected,
                    candidates={r.id: round(
                        self._effective_load(r, PREFILL), 6)
                        for r in cands})
            return

    def _remaining_deadline(self, stream: DisaggStream) -> Optional[float]:
        """The stream's unspent total budget (None = the stream set
        none, engine defaults apply).  Every engine-side leg — prefill
        submit, decode import, failover re-admissions — measures its
        deadline from its own submit, so the stream-level budget must
        shrink by the time already spent; otherwise a disagg request's
        clock restarts at the decode leg and the co-located A/B
        compares different deadline semantics.  An exhausted budget
        passes a near-zero positive value: the next expiry sweep sheds
        it with the typed error, the streaming-path contract."""
        if stream.deadline_s is None:
            return None
        return max(stream.deadline_s
                   - (time.monotonic() - stream.submitted_ts), 1e-3)

    # ---------------------------------------------------------- handoff
    def _handoff(self, prefill_rep: EngineReplica, rid: int,
                 stream: DisaggStream) -> None:
        """Move the stream's KV pages from ``prefill_rep`` to a decode
        replica.  The ``serve.handoff`` chaos site fires on the export
        leg (before the pages leave the prefill allocator) and the
        import leg (before the decode side admits); either fault
        releases everything it holds and degrades to the re-prefill
        failover."""
        from ray_tpu.util import chaos
        t0 = time.monotonic()
        try:
            chaos.maybe_fail("serve.handoff")          # export leg
            handoff = prefill_rep.engine.export_request(rid)
        except chaos.InjectedFault:
            prefill_rep.engine.release_held(rid)
            self._failover(stream, cause="handoff")
            return
        if stream.trace.sampled:
            trace_mod.record_span(
                "handoff.export", stream.trace,
                start=trace_mod.epoch_of(t0),
                dur=time.monotonic() - t0,
                replica=prefill_rep.id, pages=handoff.n_pages,
                nbytes=handoff.nbytes)
        try:
            self._import(handoff, stream, t0)
        except chaos.InjectedFault:
            self._failover(stream, cause="handoff")

    def _import(self, handoff: KVHandoff, stream: DisaggStream,
                t0: float) -> None:
        """The import leg: pick a decode replica by digest affinity
        over the handoff's chain hashes, ship only the pages it is
        missing (a fully-resident target gets metadata alone and the
        store is never touched — that is what makes warm handoffs
        near-free), and re-bind the stream to the decode pool.  The
        handle always drops on the way out, so no store object can
        outlive its handoff."""
        from ray_tpu.inference.serve_gpt import ReplicaDrainingError
        from ray_tpu.util import chaos
        chaos.maybe_fail("serve.handoff")              # import leg
        import_t0 = time.monotonic()
        remaining = stream.max_new_tokens - len(stream.generated)
        excluded: set = set()
        handle: Optional[int] = None
        try:
            while True:
                cands = self._candidates(DECODE, excluded)
                if not cands:
                    stream._fail(ReplicaUnavailableError(
                        f"no healthy decode replica accepted the "
                        f"handoff ({len(self._pools[DECODE])} in the "
                        f"pool, {len(excluded)} rejected this "
                        "attempt)", retries=stream.retries))
                    return
                replica = None
                if self.affinity:
                    replica = self._affinity_pick(handoff.chain_hashes,
                                                  cands, DECODE)
                if replica is None:
                    replica = self._pow2_pick(cands, DECODE)
                # strip the payload to what the target is MISSING: the
                # leading run of chain hashes in its digest is already
                # resident (the admission walk installs them as hits),
                # so only the pages past it — plus the partial tail —
                # ship.  Fully resident + no tail = the warm handoff:
                # metadata only, the store is never touched.
                digest = replica.prefix_digest()
                resident = 0
                for h in handoff.chain_hashes:
                    if h not in digest:
                        break
                    resident += 1
                warm = (resident == handoff.n_full_pages
                        == handoff.n_pages)
                if warm:
                    payload = handoff.strip_contents()
                else:
                    ship = handoff if resident == 0 else \
                        handoff.strip_to(range(resident,
                                               handoff.n_pages))
                    if handle is not None:   # a rejected attempt's put
                        self._store.drop(handle)
                    handle = self._store.put(ship)
                    payload = self._store.get(handle)
                try:
                    rid = replica.submit_import(
                        payload, max_new_tokens=remaining,
                        sampling=stream.sampling,
                        eos_token=stream.eos_token,
                        deadline_s=self._remaining_deadline(stream))
                except (ReplicaDrainingError, QueueFullError):
                    excluded.add(replica.id)
                    continue
                except ValueError as e:
                    # a request the decode geometry can never serve
                    # (e.g. context + remaining tokens past max_seq):
                    # typed failure on the stream, not a poll-loop
                    # crash
                    stream._fail(e)
                    return
                stream.phase = DECODE
                stream.replica_id, stream.rid = replica.id, rid
                stream.handoffs += 1
                self._by_rid[(replica.id, rid)] = stream
                self.telemetry.record_handoff(
                    n_bytes=payload.nbytes,
                    seconds=time.monotonic() - t0,
                    pages=len(payload.page_list), skipped=warm,
                    trace_id=stream.trace.trace_id)
                if stream.trace.sampled:
                    trace_mod.record_span(
                        "handoff.import", stream.trace,
                        start=trace_mod.epoch_of(import_t0),
                        dur=time.monotonic() - import_t0,
                        replica=replica.id, warm=warm,
                        nbytes=payload.nbytes,
                        pages=len(payload.page_list))
                return
        finally:
            if handle is not None:
                self._store.drop(handle)

    # --------------------------------------------------------- tick loop
    def poll(self) -> bool:
        """One fleet tick: refresh per-pool health, step every live
        replica with work (prefill pool first — its first tokens
        become this tick's handoffs), dispatch events, fail streams
        over from dead/wedged replicas.  Returns whether any replica
        made progress."""
        for pool in (PREFILL, DECODE):
            self._update_health(pool)
        progressed = False
        for pool in (PREFILL, DECODE):
            for replica in list(self._pools[pool].values()):
                if replica.id not in self._pool_of:
                    continue             # removed by a reconciler mid-poll
                if not replica.alive:
                    self._on_replica_down(replica, reap=True)
                    continue
                replica.check()
                if replica.wedged:
                    self._on_replica_down(replica, reap=False)
                    continue
                if not replica.has_work():
                    continue
                try:
                    events = replica.step()
                except BaseException:  # noqa: BLE001 — death IS the event
                    self._on_replica_down(replica, reap=True)
                    continue
                progressed = progressed or bool(events)
                for ev in events:
                    self._dispatch(replica, pool, ev)
        self._record_depths()
        return progressed

    def _dispatch(self, replica: EngineReplica, pool: str, ev) -> None:
        rid, token, done = ev
        key = (replica.id, rid)
        stream = self._by_rid.get(key)
        if stream is None:
            if pool == PREFILL and done and ev.error is None:
                # a held export whose stream vanished (cancelled
                # between submit and first token): release, don't leak
                replica.engine.release_held(rid)
            return
        if ev.error is not None:
            del self._by_rid[key]
            if isinstance(ev.error, HandoffContentMissing):
                # a warm handoff whose resident pages evaporated:
                # re-prefill (a re-route, not a failover — no budget
                # burned, the pages were simply gone)
                self.telemetry.record_retry("handoff")
                self._reroute(stream)
                return
            stream._fail(ev.error)
            return
        stream._push(token, ev.logprob)
        if pool == PREFILL:
            # first-token-stop: the event is always terminal
            del self._by_rid[key]
            if stream.complete:
                replica.engine.release_held(rid)
                stream._finish()
            else:
                self._handoff(replica, rid, stream)
        elif done:
            del self._by_rid[key]
            stream._finish()

    def _on_replica_down(self, replica: EngineReplica, *,
                         reap: bool) -> None:
        """Fail every stream bound to a dead/wedged replica over to the
        prefill pool (re-prefill from prompt + emitted tokens — the one
        failover path both pools share).  Reaping releases the corpse's
        slots/pages/prefix refs *and* any held exports."""
        bound = [(k, s) for k, s in list(self._by_rid.items())
                 if k[0] == replica.id]
        cause = "dead" if reap else "wedged"
        if not reap:
            trace_mod.anomaly("wedge", replica=replica.id,
                              bound_streams=len(bound))
        for key, stream in bound:
            del self._by_rid[key]
            if replica.alive:
                replica.engine.cancel(key[1])
            self._failover(stream, cause=cause)
        if reap and not replica.alive and not replica.reaped:
            replica.reap()

    def _failover(self, stream: DisaggStream, *,
                  cause: str = "dead") -> None:
        self.telemetry.record_retry(cause)
        self.telemetry.record_failover(cause)
        from_replica = stream.replica_id
        stream.retries += 1
        if stream.retries > self.cfg.retries:
            trace_mod.anomaly("failover_budget", trace=stream.trace,
                              retries=stream.retries - 1, cause=cause)
            stream._fail(ReplicaUnavailableError(
                f"failover budget exhausted after {stream.retries - 1} "
                f"retr{'y' if stream.retries == 2 else 'ies'} "
                "(RAY_TPU_FLEET_RETRIES)", retries=stream.retries - 1))
            return
        self._reroute(stream)
        if not stream.done:
            trace_mod.event(
                "failover", stream.trace, cause=cause,
                from_replica=from_replica,
                to_replica=stream.replica_id,
                tokens_resent=len(stream.generated),
                retry=stream.retries)

    def _reroute(self, stream: DisaggStream) -> None:
        if stream.complete:
            stream._finish()            # nothing left to decode
            return
        try:
            self._route_prefill(stream)
        except (ReplicaUnavailableError, ValueError) as e:
            stream._fail(e)

    def _cancel_stream(self, stream: DisaggStream) -> None:
        if stream.replica_id is None or stream.done:
            return
        key = (stream.replica_id, stream.rid)
        self._by_rid.pop(key, None)
        replica = self._pools.get(self._pool_of.get(stream.replica_id,
                                                    ""), {}) \
            .get(stream.replica_id)
        if replica is not None and replica.alive:
            replica.engine.cancel(stream.rid)
        stream._finish()

    # ------------------------------------------------------ observability
    def _record_ttft(self, ttft_s: float,
                     trace_id: Optional[str] = None) -> None:
        self._ttfts.append(ttft_s)
        self.telemetry.record_ttft(ttft_s, mode="disagg",
                                   trace_id=trace_id)

    def recent_ttfts(self) -> List[float]:
        return list(self._ttfts)

    def _record_depths(self) -> None:
        for pool, reps in self._pools.items():
            depth = 0
            for r in reps.values():
                if r.alive:
                    depth += r.queue_depth()
                    self.telemetry.record_queue_depth(r.id,
                                                      r.queue_depth())
                    self.telemetry.record_latency_score(
                        r.id, r.latency_score())
            self.telemetry.record_pool_depth(pool, depth)

    def quiesce(self, timeout_s: float = 5.0) -> bool:
        """Poll until no replica holds work (True when settled) — the
        post-run audit gate."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.poll()
            if not any(r.alive and r.has_work()
                       for r in self.replicas()):
                return True
            time.sleep(0.002)
        return False

    def leak_free(self) -> bool:
        """Fleet-wide invariant: no slot/page/refcount held on either
        pool (held exports count — ``EngineReplica.leak_free`` reads
        the allocator), and no handoff object still in flight in the
        store."""
        return (all(r.leak_free() for r in self.replicas())
                and self._store.in_flight == 0)

    def stats(self) -> Dict[str, Any]:
        return {
            "pools": {
                pool: {r.id: {"alive": r.alive,
                              "draining": r.draining,
                              "wedged": r.wedged,
                              "queue_depth": r.queue_depth(),
                              "latency_score": r.latency_score(),
                              "demoted": r.id in self._demoted[pool]}
                       for r in reps.values()}
                for pool, reps in self._pools.items()},
            "in_flight": len(self._by_rid),
            "handoffs_in_store": self._store.in_flight,
            "affinity": self.affinity,
            # r23: the fleet-shared KV page store, when any replica
            # tiers into one (replicas share the instance, so the
            # first is everyone's view)
            "kv_store": next(
                (r.engine.store.stats() for r in self.replicas()
                 if r.engine.store is not None), None),
        }
