"""Health-aware replica router: pow-2 choices, prefix affinity,
mid-stream failover.

The layer that makes N replicas look like one reliable service.  The
API is ``DeploymentHandle``-shaped — ``router.remote(payload)`` takes
the :class:`~ray_tpu.inference.serve_gpt.GPTDeployment` request dict
and returns a stream you iterate — but the router runs host-side over
:class:`~ray_tpu.fleet.replica.EngineReplica` objects and drives their
engine ticks itself (:meth:`FleetRouter.poll`), so every routing and
recovery decision is deterministic under a ``RAY_TPU_FAULTS`` plan.

**Routing** (per request): with affinity on, the prompt's chained page
hashes (the r12 :class:`~ray_tpu.inference.kv_cache.PrefixIndex`
keys) are matched against each healthy replica's
:meth:`~ray_tpu.fleet.replica.EngineReplica.prefix_digest`; the
longest-hit replica wins if it is under the affinity queue-depth cap —
the fleet-wide prefix cache.  Otherwise power-of-two-choices on queue
depth (SURVEY: Serve's ``pow_2_scheduler.py``): sample two, take the
shallower queue — near-least-loaded at O(1) probe cost.

**Failover**: a replica death (``serve.replica`` chaos site, or any
step raise) or a watchdog wedge mid-stream re-admits every bound
request on a healthy replica — re-prefilling from the original prompt
*plus the tokens already emitted*, with ``max_new`` reduced by the
same count, so delivery is at-most-once by construction (the stream
asserts it).  Stale events from a wedged replica that later revives
cannot reach the stream: bindings are keyed ``(replica_id, rid)`` and
dropped at failover.  ``ReplicaDrainingError`` / ``QueueFullError`` /
a ``serve.route`` submit fault are immediate re-route signals (each
replica tried at most once per attempt); only death/wedge failovers
consume the ``RAY_TPU_FLEET_RETRIES`` budget, and exhausting it — or
running out of healthy replicas — surfaces a typed
:class:`ReplicaUnavailableError` on the stream, never a hang.
"""

from __future__ import annotations

import collections
import random
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.fleet.config import FleetConfig, fleet_config
from ray_tpu.fleet.replica import EngineReplica
from ray_tpu.inference.kv_cache import PrefixIndex
from ray_tpu.inference.scheduler import QueueFullError


class ReplicaUnavailableError(RuntimeError):
    """Typed routing failure: the failover budget is exhausted or no
    healthy replica remains — the caller sees this on the stream, not
    a hang (the fleet's zero-hung-streams contract)."""

    def __init__(self, msg: str, *, retries: int = 0):
        super().__init__(msg)
        self.retries = retries


class FleetStream:
    """One routed request: iterate tokens as they land (the
    ``DeploymentResponseGenerator`` shape).  Iteration pumps the
    router's poll loop; a typed error — deadline expiry, exhausted
    failover — raises out of ``__next__``."""

    def __init__(self, router: "FleetRouter", payload: Dict[str, Any]):
        from ray_tpu.inference.serve_gpt import parse_request
        self._router = router
        self.prompt = [int(t) for t in payload["tokens"]]
        parsed = parse_request(payload)    # the deployment's parser:
        self.max_new_tokens = parsed["max_new_tokens"]  # no drift
        self.sampling = parsed["sampling"]
        self.want_logprobs = parsed["want_logprobs"]
        self.eos_token = parsed["eos_token"]
        self.ttft_deadline_s = parsed["ttft_deadline_s"]
        self.deadline_s = parsed["deadline_s"]
        self.submitted_ts = time.monotonic()
        self.first_token_ts: Optional[float] = None
        # every token the fleet has emitted for this request, in order
        # (the failover re-prefill source), with its model logprob
        # beside it; _cursor is how far the consumer has read
        self.generated: List[int] = []
        self.logprobs: List[float] = []
        self._cursor = 0
        self.done = False
        self.error: Optional[BaseException] = None
        self.retries = 0                  # death/wedge failovers only
        self.replica_id: Optional[str] = None
        self.rid: Optional[int] = None

    # ------------------------------------------------- router callbacks
    def _push(self, token: int, logprob: float) -> None:
        if len(self.generated) >= self.max_new_tokens:
            # at-most-once delivery is structural (failover re-admits
            # with max_new reduced by the emitted count) — a violation
            # is a router bug, surfaced loudly
            raise AssertionError(
                f"stream got token {len(self.generated) + 1} of "
                f"{self.max_new_tokens}: duplicate delivery after "
                "failover")
        if self.first_token_ts is None:
            self.first_token_ts = time.monotonic()
            self._router._record_ttft(
                self.first_token_ts - self.submitted_ts)
        self.generated.append(int(token))
        self.logprobs.append(float(logprob))

    def _finish(self) -> None:
        self.done = True

    def _fail(self, err: BaseException) -> None:
        self.error = err
        self.done = True

    # ---------------------------------------------------------- consume
    def __iter__(self):
        return self

    def __next__(self) -> int:
        while self._cursor >= len(self.generated):
            if self.error is not None:
                raise self.error
            if self.done:
                raise StopIteration
            if not self._router.poll():
                # no replica ticked (e.g. a wedge waiting out its
                # watchdog budget): yield the cpu instead of spinning
                time.sleep(0.001)
        tok = self.generated[self._cursor]
        lp = self.logprobs[self._cursor]
        self._cursor += 1
        # same item shape as the deployment's stream: bare token ids,
        # or {"token", "logprob"} dicts under {"logprobs": True}
        return {"token": tok, "logprob": lp} if self.want_logprobs \
            else tok

    def result(self) -> List[int]:
        """Drain to completion and return every token (raises the
        stream's typed error like iteration does)."""
        for _ in self:
            pass
        return list(self.generated)

    def close(self) -> None:
        """Abandon the stream: cancel the in-flight request so its
        slot/pages/prefix refs free within a tick."""
        self._router._cancel_stream(self)


class FleetRouter:
    """Route requests over a set of replicas and drive their ticks.

    ``replicas`` seed the fleet (the reconciler adds/removes later);
    all replicas must share page size and bucket geometry (the prefix
    hashes and re-admission lengths assume it — checked here).
    ``rng_seed`` pins the pow-2 sampling so routing distributions are
    reproducible in tests and benchmarks.
    """

    _TTFT_WINDOW = 256

    def __init__(self, replicas: List[EngineReplica], *,
                 cfg: Optional[FleetConfig] = None,
                 affinity: Optional[bool] = None,
                 rng_seed: int = 0, telemetry=None):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.cfg = cfg or fleet_config()
        self.affinity = (self.cfg.affinity if affinity is None
                         else bool(affinity))
        self._replicas: "collections.OrderedDict[str, EngineReplica]" \
            = collections.OrderedDict()
        self._rng = random.Random(rng_seed)
        # (replica_id, rid) -> stream; dropped at failover so a stale
        # event from a revived wedge can never reach a re-homed stream
        self._by_rid: Dict[Tuple[str, int], FleetStream] = {}
        self._ttfts: "collections.deque[float]" = collections.deque(
            maxlen=self._TTFT_WINDOW)
        if telemetry is None:
            from ray_tpu.telemetry.fleet import FleetTelemetry
            telemetry = FleetTelemetry()
        self.telemetry = telemetry
        self.page_size = replicas[0].engine.page_size
        self.buckets = replicas[0].engine.buckets
        for r in replicas:
            self.add_replica(r)

    # ----------------------------------------------------------- fleet
    def add_replica(self, replica: EngineReplica) -> None:
        if replica.id in self._replicas:
            raise ValueError(f"duplicate replica id {replica.id!r}")
        if replica.engine.page_size != self.page_size \
                or replica.engine.buckets != self.buckets:
            # one fleet geometry: the prefix hashes assume the page
            # size and failover re-admission assumes every replica
            # accepts the same prompt lengths
            raise ValueError(
                f"replica {replica.id!r} geometry (page_size "
                f"{replica.engine.page_size}, buckets "
                f"{replica.engine.buckets}) != fleet (page_size "
                f"{self.page_size}, buckets {self.buckets})")
        self._replicas[replica.id] = replica

    def remove_replica(self, replica_id: str) -> EngineReplica:
        """Drop a replica from routing.  Refuses while streams are
        still bound to it — scale-down must drain first (zero dropped
        streams); dead/wedged replicas are unbound by failover."""
        bound = [k for k in self._by_rid if k[0] == replica_id]
        if bound:
            raise ValueError(
                f"replica {replica_id!r} still has {len(bound)} "
                "in-flight stream(s) — drain (or fail over) first")
        # drop the gauge state too, or a long-running fleet's
        # queue-depth series grows one stale replica per restart
        self.telemetry.forget_replica(replica_id)
        return self._replicas.pop(replica_id)

    def replicas(self) -> List[EngineReplica]:
        return list(self._replicas.values())

    def bound_streams(self, replica_id: str) -> int:
        """How many in-flight streams are bound to a replica (the
        reconciler's retire gate: removal requires zero)."""
        return sum(1 for k in self._by_rid if k[0] == replica_id)

    def healthy(self) -> List[EngineReplica]:
        return [r for r in self._replicas.values()
                if r.alive and not r.draining and not r.wedged]

    # --------------------------------------------------------- routing
    def remote(self, payload: Dict[str, Any]) -> FleetStream:
        """Route one request (the ``GPTDeployment`` payload dict) and
        return its stream.  Routing failures surface as the stream's
        typed error at first iteration — the streaming-path contract
        (``QueueFullError`` precedent), never an exception here."""
        stream = FleetStream(self, payload)
        try:
            self._route(stream)
        except (ReplicaUnavailableError, ValueError) as e:
            stream._fail(e)
        return stream

    def _chain_hashes(self, prompt: List[int]) -> List[bytes]:
        """Hit-eligible chained page hashes of a prompt — the
        scheduler's own walk (shared helper, so the hashing scheme
        and the final-page eligibility rule can never drift between
        routing and admission)."""
        eligible = PrefixIndex.hit_eligible(len(prompt),
                                            self.page_size)
        return PrefixIndex.chain_hashes(prompt,
                                        self.page_size)[:eligible]

    def _affinity_pick(self, prompt, cands) -> Optional[EngineReplica]:
        hashes = self._chain_hashes(prompt)
        if not hashes:
            return None
        best, best_hits = None, 0
        for r in cands:
            digest = r.prefix_digest()
            hits = 0
            for h in hashes:
                if h not in digest:
                    break
                hits += 1
            if hits > best_hits:
                best, best_hits = r, hits
        if best is not None \
                and best.queue_depth() < self.cfg.affinity_cap:
            return best
        return None             # no hit, or the hit replica is hot

    def _pow2_pick(self, cands) -> EngineReplica:
        if len(cands) == 1:
            return cands[0]
        a, b = self._rng.sample(cands, 2)
        return a if a.queue_depth() <= b.queue_depth() else b

    def _route(self, stream: FleetStream) -> None:
        """Pick a replica and submit; draining/queue-full/route-fault
        rejections re-route immediately (each replica tried at most
        once).  Raises :class:`ReplicaUnavailableError` when no
        healthy replica accepts."""
        from ray_tpu.inference.serve_gpt import ReplicaDrainingError
        from ray_tpu.util import chaos
        # failover re-prefill: prompt plus every already-emitted token
        prompt = stream.prompt + stream.generated
        remaining = stream.max_new_tokens - len(stream.generated)
        if len(prompt) > self.buckets[-1]:
            # the grown prompt outruns the fleet's largest prefill
            # bucket: the original request was admissible but its
            # re-admission is not — a geometry limit (size buckets to
            # cover prompt + max_new when failover must always work),
            # surfaced typed instead of as a raw engine ValueError
            raise ReplicaUnavailableError(
                f"failover re-prefill needs {len(prompt)} prompt "
                f"tokens but the fleet's largest prefill bucket is "
                f"{self.buckets[-1]} — size RAY_TPU_INFER_BUCKETS to "
                "cover prompt + max_new_tokens for failover-proof "
                "requests", retries=stream.retries)
        excluded: set = set()
        while True:
            cands = [r for r in self.healthy()
                     if r.id not in excluded]
            if not cands:
                raise ReplicaUnavailableError(
                    f"no healthy replica accepted the request "
                    f"({len(self._replicas)} total, "
                    f"{len(excluded)} rejected this attempt, "
                    f"{stream.retries} failover(s) used)",
                    retries=stream.retries)
            replica = None
            if self.affinity:
                replica = self._affinity_pick(prompt, cands)
                if not excluded and stream.retries == 0:
                    # one decision per REQUEST: re-routes and failover
                    # re-admissions must not multiply-count a request
                    # in the hit-rate gauge (failovers skew toward
                    # hits — the re-prefill is resident fleet-wide —
                    # which would inflate the metric exactly when the
                    # fleet is unhealthy)
                    self.telemetry.record_affinity(
                        hit=replica is not None)
            if replica is None:
                replica = self._pow2_pick(cands)
            try:
                chaos.maybe_fail("serve.route")
                rid = replica.submit(
                    prompt, max_new_tokens=remaining,
                    sampling=stream.sampling,
                    eos_token=stream.eos_token,
                    ttft_deadline_s=stream.ttft_deadline_s,
                    deadline_s=stream.deadline_s)
            except chaos.InjectedFault:
                # a routed submit failed in flight: indistinguishable
                # from a dead target at the router — re-route
                self.telemetry.record_retry("dead")
                excluded.add(replica.id)
                continue
            except ReplicaDrainingError:
                self.telemetry.record_retry("draining")
                excluded.add(replica.id)
                continue
            except QueueFullError:
                self.telemetry.record_retry("queue_full")
                excluded.add(replica.id)
                continue
            stream.replica_id, stream.rid = replica.id, rid
            self._by_rid[(replica.id, rid)] = stream
            return

    # ------------------------------------------------------- tick loop
    def poll(self) -> bool:
        """One fleet tick: probe watchdogs, step every live replica
        with work, dispatch events, fail streams over from dead or
        wedged replicas.  Returns whether any replica made progress
        (consumers back off briefly when none did)."""
        progressed = False
        for replica in list(self._replicas.values()):
            if not replica.alive:
                self._on_replica_down(replica, reap=True)
                continue
            replica.check()
            if replica.wedged:
                self._on_replica_down(replica, reap=False)
                continue
            if not replica.has_work():
                continue
            try:
                events = replica.step()
            except BaseException:  # noqa: BLE001 — death IS the event
                self._on_replica_down(replica, reap=True)
                continue
            progressed = progressed or bool(events)
            for ev in events:
                self._dispatch(replica, ev)
        self._record_depths()
        return progressed

    def _dispatch(self, replica: EngineReplica, ev) -> None:
        rid, token, done = ev
        key = (replica.id, rid)
        stream = self._by_rid.get(key)
        if stream is None:
            return                       # cancelled/stale binding
        if ev.error is not None:
            # deadline expiry: policy shed the request (everything
            # already released engine-side) — typed error, no failover
            del self._by_rid[key]
            stream._fail(ev.error)
            return
        stream._push(token, ev.logprob)
        if done:
            del self._by_rid[key]
            stream._finish()

    def _on_replica_down(self, replica: EngineReplica,
                         *, reap: bool) -> None:
        """Fail every stream bound to a dead/wedged replica over to a
        healthy one.  Dead replicas are reaped host-side (slots/pages/
        prefix refcounts released — the corpse audits clean); a wedged
        replica keeps its engine state for the reconciler's restart,
        but its bound rids are cancelled so a revival cannot keep
        decoding for streams that have moved on."""
        bound = [(k, s) for k, s in list(self._by_rid.items())
                 if k[0] == replica.id]
        for key, stream in bound:
            del self._by_rid[key]
            if replica.alive:
                replica.engine.cancel(key[1])
            self._failover(stream)
        if reap and not replica.alive and not replica.reaped:
            replica.reap()

    def _failover(self, stream: FleetStream) -> None:
        self.telemetry.record_retry("dead")
        stream.retries += 1
        if stream.retries > self.cfg.retries:
            stream._fail(ReplicaUnavailableError(
                f"failover budget exhausted after {stream.retries - 1} "
                f"retr{'y' if stream.retries == 2 else 'ies'} "
                "(RAY_TPU_FLEET_RETRIES)", retries=stream.retries - 1))
            return
        try:
            self._route(stream)
        except (ReplicaUnavailableError, ValueError) as e:
            stream._fail(e)

    def _cancel_stream(self, stream: FleetStream) -> None:
        if stream.replica_id is None or stream.done:
            return
        key = (stream.replica_id, stream.rid)
        self._by_rid.pop(key, None)
        replica = self._replicas.get(stream.replica_id)
        if replica is not None and replica.alive:
            replica.engine.cancel(stream.rid)
        stream._finish()

    # ------------------------------------------------------ observability
    def _record_ttft(self, ttft_s: float) -> None:
        self._ttfts.append(ttft_s)

    def recent_ttfts(self) -> List[float]:
        """Recent first-token latencies (the reconciler's SLO signal
        and the bench's percentile source)."""
        return list(self._ttfts)

    def _record_depths(self) -> None:
        for r in self._replicas.values():
            if r.alive:
                self.telemetry.record_queue_depth(r.id, r.queue_depth())

    def leak_free(self) -> bool:
        """Fleet-wide invariant: no slot/page/refcount held anywhere
        (dead replicas were reaped at failover, so they audit too)."""
        return all(r.leak_free() for r in self._replicas.values())

    def stats(self) -> Dict[str, Any]:
        return {
            "replicas": {r.id: {"alive": r.alive,
                                "draining": r.draining,
                                "wedged": r.wedged,
                                "queue_depth": r.queue_depth()}
                         for r in self._replicas.values()},
            "in_flight": len(self._by_rid),
            "affinity": self.affinity,
        }
