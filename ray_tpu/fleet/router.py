"""Health-aware replica router: pow-2 choices, prefix affinity,
mid-stream failover.

The layer that makes N replicas look like one reliable service.  The
API is ``DeploymentHandle``-shaped — ``router.remote(payload)`` takes
the :class:`~ray_tpu.inference.serve_gpt.GPTDeployment` request dict
and returns a stream you iterate — but the router runs host-side over
:class:`~ray_tpu.fleet.replica.EngineReplica` objects and drives their
engine ticks itself (:meth:`FleetRouter.poll`), so every routing and
recovery decision is deterministic under a ``RAY_TPU_FAULTS`` plan.

**Routing** (per request): with affinity on, the prompt's chained page
hashes (the r12 :class:`~ray_tpu.inference.kv_cache.PrefixIndex`
keys) are matched against each healthy replica's
:meth:`~ray_tpu.fleet.replica.EngineReplica.prefix_digest`; the
longest-hit replica wins if it is under the affinity queue-depth cap —
the fleet-wide prefix cache.  Otherwise power-of-two-choices on queue
depth (SURVEY: Serve's ``pow_2_scheduler.py``): sample two, take the
shallower queue — near-least-loaded at O(1) probe cost.

**Gray failure** (r19): binary health misses the replica that is slow
without being dead — 10x tick latency still counts "alive", and tails
are gated by the slowest participant (arXiv:2011.03641).  Three
mitigations share one latency vocabulary: (1) every replica carries an
EWMA tick-latency **health score**; the pow-2 comparison weighs queue
depth by relative latency, and replicas past
``RAY_TPU_FLEET_SLOW_FACTOR``x the fleet median are **demoted** —
excluded from routing while any faster replica exists (soft: an
all-slow fleet still routes) and surfaced via :meth:`FleetRouter.
slow_replicas` for the reconciler's DEGRADED dwell.  (2) a stream
whose first token misses the rolling-p99-informed **hedge deadline**
(``RAY_TPU_FLEET_HEDGE_*``) is re-admitted on a second replica —
first responder wins, the loser is cancelled; at-most-once delivery
is preserved by the same ``(replica_id, rid)`` binding keys failover
uses (the losing binding drops before its token could land).  (3) a
hedged stream whose primary *dies* promotes the surviving binding
instead of re-routing — the hedge was the failover.

**Failover**: a replica death (``serve.replica`` chaos site, or any
step raise) or a watchdog wedge mid-stream re-admits every bound
request on a healthy replica — re-prefilling from the original prompt
*plus the tokens already emitted*, with ``max_new`` reduced by the
same count, so delivery is at-most-once by construction (the stream
asserts it).  Stale events from a wedged replica that later revives
cannot reach the stream: bindings are keyed ``(replica_id, rid)`` and
dropped at failover.  ``ReplicaDrainingError`` / ``QueueFullError`` /
a ``serve.route`` submit fault are immediate re-route signals (each
replica tried at most once per attempt); only death/wedge failovers
consume the ``RAY_TPU_FLEET_RETRIES`` budget, and exhausting it — or
running out of healthy replicas — surfaces a typed
:class:`ReplicaUnavailableError` on the stream, never a hang.
"""

from __future__ import annotations

import collections
import queue
import random
import statistics
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.adapters import AdapterUnavailableError
from ray_tpu.fleet.config import FleetConfig, fleet_config
from ray_tpu.fleet.replica import EngineReplica
from ray_tpu.inference.kv_cache import PrefixIndex
from ray_tpu.inference.scheduler import QueueFullError
from ray_tpu.telemetry import trace as trace_mod


class ReplicaUnavailableError(RuntimeError):
    """Typed routing failure: the failover budget is exhausted or no
    healthy replica remains — the caller sees this on the stream, not
    a hang (the fleet's zero-hung-streams contract)."""

    def __init__(self, msg: str, *, retries: int = 0):
        super().__init__(msg)
        self.retries = retries


class FleetStream:
    """One routed request: iterate tokens as they land (the
    ``DeploymentResponseGenerator`` shape).  Iteration pumps the
    router's poll loop; a typed error — deadline expiry, exhausted
    failover — raises out of ``__next__``."""

    def __init__(self, router: "FleetRouter", payload: Dict[str, Any]):
        from ray_tpu.inference.serve_gpt import parse_request
        self._router = router
        self.prompt = [int(t) for t in payload["tokens"]]
        parsed = parse_request(payload)    # the deployment's parser:
        self.max_new_tokens = parsed["max_new_tokens"]  # no drift
        self.sampling = parsed["sampling"]
        self.want_logprobs = parsed["want_logprobs"]
        self.eos_token = parsed["eos_token"]
        self.ttft_deadline_s = parsed["ttft_deadline_s"]
        self.deadline_s = parsed["deadline_s"]
        # r24 tracing: mint the request's TraceContext here — the
        # router boundary IS the request's birth.  The root "request"
        # span records immediately (dur=0) so a mid-request anomaly
        # dump is still rooted, and every later span parents under it.
        ctx = trace_mod.mint()
        root_id = trace_mod.record_span(
            "request", ctx, start=time.time(), dur=0.0,
            prompt_tokens=len(self.prompt),
            max_new=self.max_new_tokens)
        self.trace = ctx.child(root_id) if root_id is not None else ctx
        self.submitted_ts = time.monotonic()
        self.first_token_ts: Optional[float] = None
        # every token the fleet has emitted for this request, in order
        # (the failover re-prefill source), with its model logprob
        # beside it; _cursor is how far the consumer has read
        self.generated: List[int] = []
        self.logprobs: List[float] = []
        self.token_ts: List[float] = []   # per-token arrival stamps
        self._cursor = 0
        self.done = False
        self.error: Optional[BaseException] = None
        self.retries = 0                  # death/wedge failovers only
        self.replica_id: Optional[str] = None
        self.rid: Optional[int] = None
        # tail-latency hedge: a second concurrent binding racing the
        # primary for the first token (None when not hedged)
        self.hedge_replica_id: Optional[str] = None
        self.hedge_rid: Optional[int] = None
        self.hedges = 0                   # hedges issued for this stream

    # ------------------------------------------------- router callbacks
    def _push(self, token: int, logprob: float) -> None:
        if len(self.generated) >= self.max_new_tokens:
            # at-most-once delivery is structural (failover re-admits
            # with max_new reduced by the emitted count) — a violation
            # is a router bug, surfaced loudly
            raise AssertionError(
                f"stream got token {len(self.generated) + 1} of "
                f"{self.max_new_tokens}: duplicate delivery after "
                "failover")
        now = time.monotonic()
        if self.first_token_ts is None:
            self.first_token_ts = now
            self._router._record_ttft(now - self.submitted_ts,
                                      trace_id=self.trace.trace_id)
        self.generated.append(int(token))
        self.logprobs.append(float(logprob))
        self.token_ts.append(now)

    def _finish(self) -> None:
        self.done = True
        trace_mod.event("request_end", self.trace,
                        tokens=len(self.generated))

    def _fail(self, err: BaseException) -> None:
        self.error = err
        self.done = True
        trace_mod.event("request_error", self.trace,
                        error=type(err).__name__)

    # ---------------------------------------------------------- consume
    def __iter__(self):
        return self

    def __next__(self) -> int:
        while self._cursor >= len(self.generated):
            if self.error is not None:
                raise self.error
            if self.done:
                raise StopIteration
            if not self._router.poll():
                # no replica ticked (e.g. a wedge waiting out its
                # watchdog budget): yield the cpu instead of spinning
                time.sleep(0.001)
        tok = self.generated[self._cursor]
        lp = self.logprobs[self._cursor]
        self._cursor += 1
        # same item shape as the deployment's stream: bare token ids,
        # or {"token", "logprob"} dicts under {"logprobs": True}
        return {"token": tok, "logprob": lp} if self.want_logprobs \
            else tok

    def result(self) -> List[int]:
        """Drain to completion and return every token (raises the
        stream's typed error like iteration does)."""
        for _ in self:
            pass
        return list(self.generated)

    def close(self) -> None:
        """Abandon the stream: cancel the in-flight request so its
        slot/pages/prefix refs free within a tick."""
        self._router._cancel_stream(self)


class FleetRouter:
    """Route requests over a set of replicas and drive their ticks.

    ``replicas`` seed the fleet (the reconciler adds/removes later);
    all replicas must share page size and bucket geometry (the prefix
    hashes and re-admission lengths assume it — checked here).
    ``rng_seed`` pins the pow-2 sampling so routing distributions are
    reproducible in tests and benchmarks.

    ``concurrent_steps``: step each replica on its own worker thread
    (the engine already serves submit-vs-step concurrency — the
    deployment pump's contract) instead of sequentially inside
    :meth:`poll`.  Sequential is the default: every decision is
    deterministic under a fault plan (the r16 acceptance-test
    contract).  Concurrent exists because a *slowdown* cannot be
    modeled sequentially — a straggling replica's tick would stall
    the whole drive loop, taxing every replica equally, when the
    point of gray-failure mitigation is that it must not
    (``bench.py --gray`` and the r19 latency A/Bs run this mode;
    event interleaving is timing-dependent there, so its tests assert
    order-independent invariants).
    """

    _TTFT_WINDOW = 256

    def __init__(self, replicas: List[EngineReplica], *,
                 cfg: Optional[FleetConfig] = None,
                 affinity: Optional[bool] = None,
                 rng_seed: int = 0, telemetry=None,
                 concurrent_steps: bool = False):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.cfg = cfg or fleet_config()
        self.affinity = (self.cfg.affinity if affinity is None
                         else bool(affinity))
        self.concurrent_steps = bool(concurrent_steps)
        # concurrent mode state: a worker pool stepping replicas, the
        # completion queue workers report into, and the ids with a
        # step in flight (never step one engine from two threads)
        self._step_pool = None
        self._step_results: Optional["queue.Queue"] = None
        self._stepping: set = set()
        self._replicas: "collections.OrderedDict[str, EngineReplica]" \
            = collections.OrderedDict()
        self._rng = random.Random(rng_seed)
        # (replica_id, rid) -> stream; dropped at failover so a stale
        # event from a revived wedge can never reach a re-homed stream
        self._by_rid: Dict[Tuple[str, int], FleetStream] = {}
        self._ttfts: "collections.deque[float]" = collections.deque(
            maxlen=self._TTFT_WINDOW)
        if telemetry is None:
            from ray_tpu.telemetry.fleet import FleetTelemetry
            telemetry = FleetTelemetry()
        self.telemetry = telemetry
        # gray-failure health state, refreshed once per poll: the ids
        # currently demoted (latency score past slow_factor x median)
        # and the fleet median score backing the pow-2 penalty
        self._demoted: set = set()
        self._median_latency = 0.0
        self.page_size = replicas[0].engine.page_size
        self.buckets = replicas[0].engine.buckets
        for r in replicas:
            self.add_replica(r)

    # ----------------------------------------------------------- fleet
    def add_replica(self, replica: EngineReplica) -> None:
        if replica.id in self._replicas:
            raise ValueError(f"duplicate replica id {replica.id!r}")
        if replica.engine.page_size != self.page_size \
                or replica.engine.buckets != self.buckets:
            # one fleet geometry: the prefix hashes assume the page
            # size and failover re-admission assumes every replica
            # accepts the same prompt lengths
            raise ValueError(
                f"replica {replica.id!r} geometry (page_size "
                f"{replica.engine.page_size}, buckets "
                f"{replica.engine.buckets}) != fleet (page_size "
                f"{self.page_size}, buckets {self.buckets})")
        self._replicas[replica.id] = replica

    def remove_replica(self, replica_id: str) -> EngineReplica:
        """Drop a replica from routing.  Refuses while streams are
        still bound to it — scale-down must drain first (zero dropped
        streams); dead/wedged replicas are unbound by failover."""
        bound = [k for k in self._by_rid if k[0] == replica_id]
        if bound:
            raise ValueError(
                f"replica {replica_id!r} still has {len(bound)} "
                "in-flight stream(s) — drain (or fail over) first")
        # drop the gauge state too, or a long-running fleet's
        # queue-depth series grows one stale replica per restart
        self.telemetry.forget_replica(replica_id)
        return self._replicas.pop(replica_id)

    def replicas(self) -> List[EngineReplica]:
        return list(self._replicas.values())

    def bound_streams(self, replica_id: str) -> int:
        """How many in-flight streams are bound to a replica (the
        reconciler's retire gate: removal requires zero)."""
        return sum(1 for k in self._by_rid if k[0] == replica_id)

    def healthy(self) -> List[EngineReplica]:
        return [r for r in self._replicas.values()
                if r.alive and not r.draining and not r.wedged]

    # ---------------------------------------------------- health scoring
    def _update_health(self) -> None:
        """Refresh the demoted set from live latency scores (once per
        poll).  A replica is demoted while its EWMA tick latency
        exceeds ``slow_factor`` x the fleet median score; uniform
        slowness moves the median with it, so a fleet that is *all*
        slow (shared cause: thermal throttle, noisy host) demotes
        nobody — demotion is for the outlier, the gray failure."""
        factor = self.cfg.slow_factor
        newly: set = set()
        med = 0.0
        if factor > 0:
            scored = [(r.id, r.latency_score()) for r in self.healthy()]
            scores = [s for _, s in scored if s > 0]
            if len(scores) >= 2:
                # median_low: an even fleet takes the lower middle, so
                # one outlier in a 2-replica fleet still stands out
                # against the healthy score instead of their average
                med = statistics.median_low(scores)
                if med > 0:
                    newly = {rid for rid, s in scored
                             if s > factor * med}
        for rid in sorted(newly - self._demoted):
            self.telemetry.record_demotion(rid)
            trace_mod.anomaly("demotion", replica=rid,
                              median_latency_s=med, slow_factor=factor)
        self._demoted = newly
        self._median_latency = med

    def slow_replicas(self) -> set:
        """Ids currently demoted for latency (the reconciler's
        DEGRADED signal — dwell-gating is the reconciler's job; this
        is the instantaneous verdict)."""
        return set(self._demoted)

    def _effective_load(self, r: EngineReplica) -> float:
        """Queue depth weighted by relative latency: the pow-2 signal.
        ``depth + 1`` so an idle-but-slow replica still loses to an
        idle fast one; the latency ratio only ever penalizes (a
        faster-than-median replica is not rewarded — depth stays the
        primary balance signal)."""
        med = self._median_latency
        score = r.latency_score()
        rel = score / med if (med > 0 and score > 0) else 1.0
        return (r.queue_depth() + 1) * max(rel, 1.0)

    # --------------------------------------------------------- routing
    def remote(self, payload: Dict[str, Any]) -> FleetStream:
        """Route one request (the ``GPTDeployment`` payload dict) and
        return its stream.  Routing failures surface as the stream's
        typed error at first iteration — the streaming-path contract
        (``QueueFullError`` precedent), never an exception here."""
        stream = FleetStream(self, payload)
        try:
            self._route(stream)
        except (ReplicaUnavailableError, ValueError) as e:
            stream._fail(e)
        return stream

    def _chain_hashes(self, prompt: List[int],
                      salt: bytes = b"") -> List[bytes]:
        """Hit-eligible chained page hashes of a prompt — the
        scheduler's own walk (shared helper, so the hashing scheme
        and the final-page eligibility rule can never drift between
        routing and admission).  ``salt`` (r25) is the per-tenant
        chain salt: a multi-tenant request's routing-side hashes must
        match the salted entries its admission will register, or
        affinity would score adapter traffic against base K/V it can
        never legally hit."""
        eligible = PrefixIndex.hit_eligible(len(prompt),
                                            self.page_size)
        return PrefixIndex.chain_hashes(prompt, self.page_size,
                                        salt=salt)[:eligible]

    def _adapter_salt(self, model_id: Optional[str]) -> bytes:
        """The routing-side view of a tenant's prefix-chain salt,
        through the fleet-shared adapter store (the first replica
        wired to one — replicas of a fleet share the instance)."""
        if not model_id:
            return b""
        store = next(
            (getattr(r.engine, "adapter_store", None)
             for r in self._replicas.values()
             if getattr(r.engine, "adapter_store", None) is not None),
            None)
        return store.salt_for(model_id) if store is not None else b""

    # Tier-aware affinity weights (r23): an HBM-resident page is a
    # pure refcount bump; a host-DRAM page pays one host->device page
    # copy, so it is worth most-but-not-all of an HBM hit — a replica
    # holding the whole prefix spilled still beats one holding a short
    # resident stub.  The store tier is deliberately weightless: any
    # replica fetches a store page at the same price, so store
    # coverage cannot differentiate candidates (those requests fall
    # through to the pow-2 load pick and warm whichever replica wins).
    TIER_WEIGHT_HBM = 1.0
    TIER_WEIGHT_DRAM = 0.8
    # Adapter residency (r25): a resident tenant skips the store
    # fetch + bank install a cold replica would pay — worth a couple
    # of page hits, but a long prefix hit should still dominate (the
    # saved prefill FLOPs scale with the prefix; the adapter load is
    # one bounded host-side install)
    ADAPTER_WEIGHT = 2.0

    def _affinity_pick(self, prompt, cands,
                       model_id: Optional[str] = None
                       ) -> Optional[EngineReplica]:
        """The tier-aware cost model over the r16 prefix-affinity
        pick: candidates score by how much re-prefill their warm tiers
        save (HBM hit > DRAM hit > nothing; ties break toward the
        shallower queue), and the winner still yields to pow-2 when
        its queue is past the affinity cap — a hot cache must not
        become a hot spot.  Multi-tenant requests (r25) compose an
        adapter-residency bonus into the same score — their prefix
        hashes are salted per tenant, so the two signals can never
        double-count the same pages — unless
        ``RAY_TPU_FLEET_ADAPTER_AFFINITY=0`` pins the residency-blind
        A/B arm."""
        hashes = self._chain_hashes(prompt,
                                    salt=self._adapter_salt(model_id))
        score_adapters = (model_id is not None
                          and self.cfg.adapter_affinity)
        if not hashes and not score_adapters:
            return None
        best, best_score = None, 0.0
        for r in cands:
            n_hbm, n_dram = r.tier_hits(hashes) if hashes else (0, 0)
            score = (n_hbm * self.TIER_WEIGHT_HBM
                     + n_dram * self.TIER_WEIGHT_DRAM)
            if score_adapters and model_id in r.adapter_digest():
                score += self.ADAPTER_WEIGHT
            if score > best_score or (
                    score == best_score and best is not None
                    and score > 0.0
                    and r.queue_depth() < best.queue_depth()):
                best, best_score = r, score
        if best is not None \
                and best.queue_depth() < self.cfg.affinity_cap:
            return best
        return None             # no hit, or the hit replica is hot

    def _pow2_pick(self, cands) -> EngineReplica:
        if len(cands) == 1:
            return cands[0]
        a, b = self._rng.sample(cands, 2)
        return a if self._effective_load(a) <= self._effective_load(b) \
            else b

    def _route(self, stream: FleetStream) -> None:
        """Pick a replica and submit; draining/queue-full/route-fault
        rejections re-route immediately (each replica tried at most
        once).  Raises :class:`ReplicaUnavailableError` when no
        healthy replica accepts."""
        from ray_tpu.inference.serve_gpt import ReplicaDrainingError
        from ray_tpu.util import chaos
        # failover re-prefill: prompt plus every already-emitted token
        prompt = stream.prompt + stream.generated
        remaining = stream.max_new_tokens - len(stream.generated)
        if len(prompt) > self.buckets[-1]:
            # the grown prompt outruns the fleet's largest prefill
            # bucket: the original request was admissible but its
            # re-admission is not — a geometry limit (size buckets to
            # cover prompt + max_new when failover must always work),
            # surfaced typed instead of as a raw engine ValueError
            raise ReplicaUnavailableError(
                f"failover re-prefill needs {len(prompt)} prompt "
                f"tokens but the fleet's largest prefill bucket is "
                f"{self.buckets[-1]} — size RAY_TPU_INFER_BUCKETS to "
                "cover prompt + max_new_tokens for failover-proof "
                "requests", retries=stream.retries)
        excluded: set = set()
        route_t0 = time.monotonic()
        rejected: List[str] = []   # cause-tagged per-attempt rejections
        while True:
            cands = [r for r in self.healthy()
                     if r.id not in excluded]
            if not cands:
                raise ReplicaUnavailableError(
                    f"no healthy replica accepted the request "
                    f"({len(self._replicas)} total, "
                    f"{len(excluded)} rejected this attempt, "
                    f"{stream.retries} failover(s) used)",
                    retries=stream.retries)
            # gray-failure demotion: route past latency-demoted
            # replicas while any faster one exists — but an all-slow
            # candidate set still routes (soft demotion, never a
            # dead-end)
            fast = [r for r in cands if r.id not in self._demoted]
            cands = fast or cands
            replica = None
            if self.affinity:
                replica = self._affinity_pick(
                    prompt, cands, model_id=stream.sampling.model_id)
                if not excluded and stream.retries == 0:
                    # one decision per REQUEST: re-routes and failover
                    # re-admissions must not multiply-count a request
                    # in the hit-rate gauge (failovers skew toward
                    # hits — the re-prefill is resident fleet-wide —
                    # which would inflate the metric exactly when the
                    # fleet is unhealthy)
                    self.telemetry.record_affinity(
                        hit=replica is not None)
            if replica is None:
                replica = self._pow2_pick(cands)
            try:
                chaos.maybe_fail("serve.route")
                rid = replica.submit(
                    prompt, max_new_tokens=remaining,
                    sampling=stream.sampling,
                    eos_token=stream.eos_token,
                    ttft_deadline_s=stream.ttft_deadline_s,
                    deadline_s=stream.deadline_s,
                    trace_ctx=stream.trace)
            except chaos.InjectedFault:
                # a routed submit failed in flight: indistinguishable
                # from a dead target at the router — re-route
                self.telemetry.record_retry("dead")
                rejected.append(f"dead:{replica.id}")
                excluded.add(replica.id)
                continue
            except ReplicaDrainingError:
                self.telemetry.record_retry("draining")
                rejected.append(f"draining:{replica.id}")
                excluded.add(replica.id)
                continue
            except QueueFullError:
                self.telemetry.record_retry("queue_full")
                rejected.append(f"queue_full:{replica.id}")
                excluded.add(replica.id)
                continue
            except AdapterUnavailableError:
                # this replica cannot serve the tenant (no adapter
                # support / bank full of pinned tenants): try the
                # others — only when EVERY replica rejects does the
                # typed error surface (via the empty-candidates raise)
                self.telemetry.record_retry("adapter")
                rejected.append(f"adapter:{replica.id}")
                excluded.add(replica.id)
                continue
            stream.replica_id, stream.rid = replica.id, rid
            self._by_rid[(replica.id, rid)] = stream
            if stream.trace.sampled:
                trace_mod.record_span(
                    "route", stream.trace,
                    start=trace_mod.epoch_of(route_t0),
                    dur=time.monotonic() - route_t0,
                    picked=replica.id, attempt=stream.retries,
                    rejected=rejected,
                    candidates={r.id: round(self._effective_load(r), 6)
                                for r in cands})
            return

    # --------------------------------------------------------- hedging
    def hedge_deadline_s(self) -> float:
        """How long a stream may wait for its first token before the
        router races a second replica: ``hedge_factor`` x the rolling
        p99 TTFT once enough samples exist, floored at ``hedge_min``
        (which is also the whole deadline on a cold fleet — a fleet
        with no latency history must not hedge everything)."""
        if len(self._ttfts) >= 16:
            srt = sorted(self._ttfts)
            p99 = srt[min(len(srt) - 1, int(0.99 * len(srt)))]
            return max(self.cfg.hedge_min,
                       self.cfg.hedge_factor * p99)
        return self.cfg.hedge_min

    def _maybe_hedge(self) -> None:
        """Re-admit over-deadline first-token waiters on a second
        replica.  The hedge races the primary: both bindings map to
        the stream, the first token resolves the race and cancels the
        loser — delivery stays at-most-once because exactly one
        binding survives to push tokens.

        Two gates keep hedging from amplifying load (the Tail-at-Scale
        failure mode: a saturated fleet hedging itself deeper into
        saturation): the stream must be past the p99-informed
        deadline, AND the hedge target must have **spare capacity
        now** (an empty waiting queue) — a stream that is slow because
        the whole fleet is queued gains nothing from one more queue
        slot, only the stream stuck behind a *relatively* slow replica
        does.  Capacity is observable before the straggler's first
        slow tick even completes, so the gate protects a cold fleet
        without blinding the hedge exactly when it is needed."""
        now = time.monotonic()
        deadline = self.hedge_deadline_s()
        for stream in list(dict.fromkeys(self._by_rid.values())):
            # hedges > 0: a stream races at most ONE hedge in its
            # lifetime.  Without the cap, a leg whose TTFT deadline
            # expires is absorbed by the partner and the stream
            # re-hedges next poll — an unmeetable deadline would spin
            # fresh admissions forever (each restarts the engine-side
            # deadline clock) instead of surfacing the typed error.
            if (stream.done or stream.first_token_ts is not None
                    or stream.hedge_rid is not None
                    or stream.hedges > 0
                    or stream.replica_id is None
                    or now - stream.submitted_ts < deadline):
                continue
            self._submit_hedge(stream)

    def _submit_hedge(self, stream: FleetStream) -> None:
        from ray_tpu.inference.serve_gpt import ReplicaDrainingError
        cands = [r for r in self.healthy()
                 if r.id != stream.replica_id]
        # fastest-first, demoted last: the hedge exists to dodge the
        # slow replica — racing it against another slow one is waste
        cands.sort(key=lambda r: (r.id in self._demoted,
                                  self._effective_load(r)))
        if not cands or cands[0].waiting_depth() > 0:
            return      # no spare capacity anywhere: don't amplify
        for replica in cands:
            if replica.waiting_depth() > 0:
                # the capacity gate holds per candidate, not just for
                # the best one: a rejected submit must not fall
                # through to a queued replica — that's the exact load
                # amplification the gate exists to prevent
                continue
            try:
                rid = replica.submit(
                    stream.prompt,
                    max_new_tokens=stream.max_new_tokens,
                    sampling=stream.sampling,
                    eos_token=stream.eos_token,
                    ttft_deadline_s=stream.ttft_deadline_s,
                    deadline_s=stream.deadline_s,
                    trace_ctx=stream.trace)
            except (ReplicaDrainingError, QueueFullError, ValueError,
                    AdapterUnavailableError):
                continue              # best-effort: primary still runs
            stream.hedge_replica_id, stream.hedge_rid = replica.id, rid
            stream.hedges += 1
            self._by_rid[(replica.id, rid)] = stream
            self.telemetry.record_hedge("issued")
            trace_mod.event("hedge_issued", stream.trace,
                            hedge_replica=replica.id,
                            primary_replica=stream.replica_id,
                            waited_s=(time.monotonic()
                                      - stream.submitted_ts))
            return

    def _other_binding(self, stream: FleetStream,
                       key: Tuple[str, int]) -> Optional[Tuple[str, int]]:
        """The stream's still-bound race partner of ``key`` (None when
        the stream is not hedged or the partner is already unbound)."""
        if stream.hedge_rid is None:
            return None
        primary = (stream.replica_id, stream.rid)
        hedge = (stream.hedge_replica_id, stream.hedge_rid)
        other = hedge if key == primary else (
            primary if key == hedge else None)
        return other if other is not None and other in self._by_rid \
            else None

    def _resolve_hedge(self, stream: FleetStream,
                       winner: Tuple[str, int],
                       loser: Optional[Tuple[str, int]]) -> None:
        """Settle a hedge race: the winning binding becomes the
        stream's one binding; the loser (if still bound) is unbound
        and cancelled engine-side so its slot/pages/prefix refs free
        within a tick."""
        hedge_won = winner == (stream.hedge_replica_id,
                               stream.hedge_rid)
        if loser is not None:
            self._by_rid.pop(loser, None)
            rep = self._replicas.get(loser[0])
            if rep is not None and rep.alive:
                rep.engine.cancel(loser[1])
        stream.replica_id, stream.rid = winner
        stream.hedge_replica_id = stream.hedge_rid = None
        self.telemetry.record_hedge("won" if hedge_won else "wasted")
        self.telemetry.record_hedge_won(
            "hedge" if hedge_won else "primary")
        trace_mod.event("hedge_resolved", stream.trace,
                        winner="hedge" if hedge_won else "primary",
                        replica=winner[0])

    # ------------------------------------------------------- tick loop
    def quiesce(self, timeout_s: float = 5.0) -> bool:
        """Poll until no step is in flight and no replica holds work
        (True when settled).  Post-run audits need this in
        ``concurrent_steps`` mode: a cancelled hedge loser's tick may
        still be sleeping in a worker when the last stream finishes,
        and ``leak_free`` must not read an engine mid-step."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.poll()
            if not self._stepping and not any(
                    r.alive and r.has_work()
                    for r in self._replicas.values()):
                return True
            time.sleep(0.002)
        return False

    def close(self) -> None:
        """Release the concurrent-mode step pool (idempotent; a no-op
        for sequential routers).  Worker threads are only created by
        ``concurrent_steps`` polling — a dropped router would
        otherwise park them until GC/interpreter exit."""
        pool, self._step_pool = self._step_pool, None
        # _stepping is NOT cleared: shutdown(wait=False) leaves already-
        # running steps running, and a poll() after close() (a consumer
        # draining a leftover stream) must still see their replicas as
        # in flight — clearing would let it double-step an engine.  No
        # id can be stranded either: the pool holds >= one worker per
        # replica, so every submitted step runs (cancel_futures never
        # finds a queued one) and its completion drain discards the id.
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    def poll(self) -> bool:
        """One fleet tick: refresh health scores, hedge over-deadline
        first-token waiters, probe watchdogs, step every live replica
        with work, dispatch events, fail streams over from dead or
        wedged replicas.  Returns whether any replica made progress
        (consumers back off briefly when none did)."""
        self._update_health()
        if self.cfg.hedge:
            self._maybe_hedge()
        progressed = (self._poll_concurrent() if self.concurrent_steps
                      else self._poll_sequential())
        self._record_depths()
        return progressed

    def _poll_sequential(self) -> bool:
        progressed = False
        for replica in list(self._replicas.values()):
            if not replica.alive:
                self._on_replica_down(replica, reap=True)
                continue
            replica.check()
            if replica.wedged:
                self._on_replica_down(replica, reap=False)
                continue
            if not replica.has_work():
                continue
            try:
                events = replica.step()
            except BaseException:  # noqa: BLE001 — death IS the event
                self._on_replica_down(replica, reap=True)
                continue
            progressed = progressed or bool(events)
            for ev in events:
                self._dispatch(replica, ev)
        return progressed

    def _poll_concurrent(self) -> bool:
        """Concurrent-mode tick: launch one worker-thread step per
        idle replica with work (the engine's submit-vs-step lock makes
        main-thread admissions safe against it), then drain whatever
        steps have completed and dispatch their events here on the
        poll thread — all stream/binding state stays single-threaded.
        A straggling replica's slow tick occupies only its own worker;
        the fleet keeps polling at the healthy replicas' pace (the
        whole point of the mode — see the class docstring)."""
        if self._step_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._step_pool = ThreadPoolExecutor(
                max_workers=max(4, len(self._replicas) + 2),
                thread_name_prefix="fleet-step")
            self._step_results = queue.Queue()

        def run_step(rep: EngineReplica) -> None:
            try:
                self._step_results.put((rep, rep.step(), None))
            except BaseException as e:  # noqa: BLE001 — death IS the event
                self._step_results.put((rep, None, e))

        for replica in list(self._replicas.values()):
            in_flight = replica.id in self._stepping
            if not replica.alive:
                # an in-flight step's death report arrives via the
                # completion queue; handling it here too would be fine
                # (idempotent) but noisy
                if not in_flight:
                    self._on_replica_down(replica, reap=True)
                continue
            replica.check()
            if replica.wedged:
                # a hung in-flight step IS the wedge: re-home the
                # streams now — a late completion's events drop on the
                # stale (replica_id, rid) bindings, the r16 invariant
                self._on_replica_down(replica, reap=False)
                continue
            if in_flight or not replica.has_work():
                continue
            self._stepping.add(replica.id)
            self._step_pool.submit(run_step, replica)

        progressed = False
        while True:
            try:
                replica, events, err = self._step_results.get_nowait()
            except queue.Empty:
                break
            self._stepping.discard(replica.id)
            if err is not None:
                self._on_replica_down(replica, reap=True)
                continue
            progressed = progressed or bool(events)
            for ev in events:
                self._dispatch(replica, ev)
        return progressed

    def _dispatch(self, replica: EngineReplica, ev) -> None:
        rid, token, done = ev
        key = (replica.id, rid)
        stream = self._by_rid.get(key)
        if stream is None:
            return                       # cancelled/stale binding
        if ev.error is not None:
            del self._by_rid[key]
            other = self._other_binding(stream, key)
            if other is not None:
                # one leg of a hedge race expired (e.g. its TTFT
                # deadline): the partner is still decoding — let it
                # carry the stream instead of surfacing the error
                self._resolve_hedge(stream, winner=other, loser=None)
                return
            # deadline expiry: policy shed the request (everything
            # already released engine-side) — typed error, no failover
            stream._fail(ev.error)
            return
        if stream.first_token_ts is None and stream.hedge_rid is not None:
            # the first token resolves the hedge race: this binding
            # wins, the other is unbound BEFORE any of its tokens
            # could land (at-most-once stays structural)
            self._resolve_hedge(stream, winner=key,
                                loser=self._other_binding(stream, key))
        stream._push(token, ev.logprob)
        if done:
            del self._by_rid[key]
            stream._finish()

    def _on_replica_down(self, replica: EngineReplica,
                         *, reap: bool) -> None:
        """Fail every stream bound to a dead/wedged replica over to a
        healthy one.  Dead replicas are reaped host-side (slots/pages/
        prefix refcounts released — the corpse audits clean); a wedged
        replica keeps its engine state for the reconciler's restart,
        but its bound rids are cancelled so a revival cannot keep
        decoding for streams that have moved on."""
        cause = "dead" if reap else "wedged"
        bound = [(k, s) for k, s in list(self._by_rid.items())
                 if k[0] == replica.id]
        if not reap:
            # a watchdog wedge is an anomaly trigger even with nothing
            # bound: the record of what the fleet was doing when the
            # step loop froze is the whole point of the recorder
            trace_mod.anomaly("wedge", replica=replica.id,
                              bound_streams=len(bound))
        for key, stream in bound:
            del self._by_rid[key]
            if replica.alive:
                replica.engine.cancel(key[1])
            other = self._other_binding(stream, key)
            if other is not None:
                # a hedged stream lost one leg to the death/wedge: the
                # surviving binding IS the failover — promote it, no
                # re-route ("won" when the hedge saved the stream)
                self._resolve_hedge(stream, winner=other, loser=None)
                continue
            self._failover(stream, cause=cause)
        if reap and not replica.alive and not replica.reaped:
            replica.reap()

    def _failover(self, stream: FleetStream, *,
                  cause: str = "dead") -> None:
        self.telemetry.record_retry("dead")
        self.telemetry.record_failover(cause)
        from_replica = stream.replica_id
        stream.retries += 1
        if stream.retries > self.cfg.retries:
            trace_mod.anomaly("failover_budget", trace=stream.trace,
                              retries=stream.retries - 1, cause=cause)
            stream._fail(ReplicaUnavailableError(
                f"failover budget exhausted after {stream.retries - 1} "
                f"retr{'y' if stream.retries == 2 else 'ies'} "
                "(RAY_TPU_FLEET_RETRIES)", retries=stream.retries - 1))
            return
        try:
            self._route(stream)
        except (ReplicaUnavailableError, ValueError) as e:
            stream._fail(e)
            return
        trace_mod.event("failover", stream.trace, cause=cause,
                        from_replica=from_replica,
                        to_replica=stream.replica_id,
                        tokens_resent=len(stream.generated),
                        retry=stream.retries)

    def _cancel_stream(self, stream: FleetStream) -> None:
        if stream.replica_id is None or stream.done:
            return
        for rep_id, rid in ((stream.replica_id, stream.rid),
                            (stream.hedge_replica_id,
                             stream.hedge_rid)):
            if rid is None:
                continue
            self._by_rid.pop((rep_id, rid), None)
            replica = self._replicas.get(rep_id)
            if replica is not None and replica.alive:
                replica.engine.cancel(rid)
        stream.hedge_replica_id = stream.hedge_rid = None
        stream._finish()

    # ------------------------------------------------------ observability
    def _record_ttft(self, ttft_s: float,
                     trace_id: Optional[str] = None) -> None:
        self._ttfts.append(ttft_s)
        # the single-pool arm of the r20 TTFT-by-pool-mode split (the
        # disagg router records mode="disagg"); the trace id rides the
        # histogram as an exemplar (r24)
        self.telemetry.record_ttft(ttft_s, mode="colocated",
                                   trace_id=trace_id)

    def recent_ttfts(self) -> List[float]:
        """Recent first-token latencies (the reconciler's SLO signal
        and the bench's percentile source)."""
        return list(self._ttfts)

    def _record_depths(self) -> None:
        for r in self._replicas.values():
            if r.alive:
                self.telemetry.record_queue_depth(r.id, r.queue_depth())
                self.telemetry.record_latency_score(
                    r.id, r.latency_score())

    def leak_free(self) -> bool:
        """Fleet-wide invariant: no slot/page/refcount held anywhere
        (dead replicas were reaped at failover, so they audit too)."""
        return all(r.leak_free() for r in self._replicas.values())

    def stats(self) -> Dict[str, Any]:
        return {
            "replicas": {r.id: {"alive": r.alive,
                                "draining": r.draining,
                                "wedged": r.wedged,
                                "queue_depth": r.queue_depth(),
                                "latency_score": r.latency_score(),
                                "demoted": r.id in self._demoted}
                         for r in self._replicas.values()},
            "in_flight": len(self._by_rid),
            "affinity": self.affinity,
            "hedge_deadline_s": self.hedge_deadline_s(),
            # r23: the fleet-shared KV page store, when any replica
            # tiers into one (replicas share the instance, so the
            # first is everyone's view)
            "kv_store": next(
                (r.engine.store.stats()
                 for r in self._replicas.values()
                 if r.engine.store is not None), None),
            # r25: the fleet-shared adapter store (same one-instance
            # convention as kv_store)
            "adapter_store": next(
                (getattr(r.engine, "adapter_store", None).stats()
                 for r in self._replicas.values()
                 if getattr(r.engine, "adapter_store", None)
                 is not None), None),
        }
