"""``ray_tpu.fleet`` — fleet-scale serving: N replicas as one service.

Everything below the replica was built in r10–r15 (the engine, the
prefix cache, deadlines/drain/watchdog); this package is the layer
above it:

- :class:`~ray_tpu.fleet.router.FleetRouter` — a
  ``DeploymentHandle``-shaped router over
  :class:`~ray_tpu.fleet.replica.EngineReplica` objects:
  power-of-two-choices on queue depth, **prefix affinity** (prompts
  route to the replica whose r12 prefix index already holds their
  pages — the cache works fleet-wide), and **mid-stream failover**
  (a dead or wedged replica's streams re-admit on a healthy one,
  re-prefilling prompt + already-emitted tokens; at-most-once token
  delivery, typed :class:`~ray_tpu.fleet.router.
  ReplicaUnavailableError` only when retries exhaust).
- :class:`~ray_tpu.fleet.reconciler.Reconciler` — an
  autoscaler-v2-style instance state machine (STARTING → RUNNING →
  DRAINING → STOPPED / WEDGED → RESTARTING, plus the r19 gray-failure
  arm RUNNING ⇄ DEGRADED → DRAINING): watchdog-signalled restarts
  with capped backoff, dwell-gated drain-restart of chronically slow
  replicas, queue-depth / TTFT-SLO scale-up, drain-based
  zero-dropped-streams scale-down, anti-flap dwell.

The router is also gray-failure tolerant (r19): per-replica EWMA
tick-latency health scores penalize slow replicas in the pow-2 pick
and demote outliers past ``RAY_TPU_FLEET_SLOW_FACTOR``x the fleet
median, and over-deadline first-token waiters are **hedged** on a
second replica (first responder wins, loser cancelled —
``RAY_TPU_FLEET_HEDGE_*``).

r20 adds **disaggregated prefill/decode serving**
(:mod:`~ray_tpu.fleet.disagg`): a :class:`~ray_tpu.fleet.disagg.
DisaggRouter` fronting a prefill pool (streams end at the first
token) and a decode pool that imports the handed-off KV pages —
content-addressed, refcounted, moved through the object store
(:class:`~ray_tpu.fleet.disagg.HandoffStore`), with digest-affinity
routing making warm handoffs metadata-only — and per-pool
:class:`~ray_tpu.fleet.disagg.PoolView` adapters so the same
reconciler scales the prefill pool on queue depth/TTFT and the decode
pool on slot occupancy.

Recovery invariants are proven under deterministic ``RAY_TPU_FAULTS``
plans (sites ``serve.replica`` / ``serve.route`` / ``serve.tick`` /
``serve.handoff`` in :mod:`ray_tpu.util.chaos`).  Config via
``RAY_TPU_FLEET_*`` (:func:`fleet_config`).
"""

from ray_tpu.fleet.config import FleetConfig, fleet_config  # noqa: F401
from ray_tpu.fleet.disagg import (DisaggRouter,  # noqa: F401
                                  DisaggStream, HandoffStore, PoolView)
from ray_tpu.fleet.reconciler import (DEGRADED, DRAINING,  # noqa: F401
                                      RESTARTING, RUNNING, STARTING,
                                      STOPPED, WEDGED, Instance,
                                      Reconciler)
from ray_tpu.fleet.replica import EngineReplica  # noqa: F401
from ray_tpu.fleet.router import (FleetRouter,  # noqa: F401
                                  FleetStream,
                                  ReplicaUnavailableError)

__all__ = [
    "FleetConfig", "fleet_config",
    "EngineReplica", "FleetRouter", "FleetStream",
    "ReplicaUnavailableError",
    "DisaggRouter", "DisaggStream", "HandoffStore", "PoolView",
    "Reconciler", "Instance",
    "STARTING", "RUNNING", "DRAINING", "STOPPED", "WEDGED",
    "RESTARTING", "DEGRADED",
]
