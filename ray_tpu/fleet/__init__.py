"""``ray_tpu.fleet`` — fleet-scale serving: N replicas as one service.

Everything below the replica was built in r10–r15 (the engine, the
prefix cache, deadlines/drain/watchdog); this package is the layer
above it:

- :class:`~ray_tpu.fleet.router.FleetRouter` — a
  ``DeploymentHandle``-shaped router over
  :class:`~ray_tpu.fleet.replica.EngineReplica` objects:
  power-of-two-choices on queue depth, **prefix affinity** (prompts
  route to the replica whose r12 prefix index already holds their
  pages — the cache works fleet-wide), and **mid-stream failover**
  (a dead or wedged replica's streams re-admit on a healthy one,
  re-prefilling prompt + already-emitted tokens; at-most-once token
  delivery, typed :class:`~ray_tpu.fleet.router.
  ReplicaUnavailableError` only when retries exhaust).
- :class:`~ray_tpu.fleet.reconciler.Reconciler` — an
  autoscaler-v2-style instance state machine (STARTING → RUNNING →
  DRAINING → STOPPED / WEDGED → RESTARTING): watchdog-signalled
  restarts with capped backoff, queue-depth / TTFT-SLO scale-up,
  drain-based zero-dropped-streams scale-down, anti-flap dwell.

Recovery invariants are proven under deterministic ``RAY_TPU_FAULTS``
plans (sites ``serve.replica`` / ``serve.route`` in
:mod:`ray_tpu.util.chaos`).  Config via ``RAY_TPU_FLEET_*``
(:func:`fleet_config`).
"""

from ray_tpu.fleet.config import FleetConfig, fleet_config  # noqa: F401
from ray_tpu.fleet.reconciler import (DRAINING, RESTARTING,  # noqa: F401
                                      RUNNING, STARTING, STOPPED,
                                      WEDGED, Instance, Reconciler)
from ray_tpu.fleet.replica import EngineReplica  # noqa: F401
from ray_tpu.fleet.router import (FleetRouter,  # noqa: F401
                                  FleetStream,
                                  ReplicaUnavailableError)

__all__ = [
    "FleetConfig", "fleet_config",
    "EngineReplica", "FleetRouter", "FleetStream",
    "ReplicaUnavailableError",
    "Reconciler", "Instance",
    "STARTING", "RUNNING", "DRAINING", "STOPPED", "WEDGED",
    "RESTARTING",
]
