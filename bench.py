"""Headline benchmark: GPT-2 (124M) training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference's north-star (BASELINE.json) is per-device training
throughput matching H100+NCCL.  Baseline constant below is the per-H100
GPT-2-small bf16 DDP throughput (~255k tokens/s/GPU ≈ 190 TFLOP/s
effective at 6*N FLOPs/token); vs_baseline = ours / that.  Measured on
whatever accelerator jax exposes (TPU chip under axon; CPU fallback for
smoke runs scales the model down).
"""

from __future__ import annotations

import json
import sys
import time

H100_GPT2_TOKENS_PER_SEC = 255_000.0

# bf16 peak of the chip families we may land on (for the MFU figure)
_CHIP_PEAK_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0, "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0, "v6e": 918.0,
}


def _chip_peak(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _CHIP_PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return 197.0


def main():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    platform = devices[0].platform
    on_accel = platform not in ("cpu",)
    quick = "--quick" in sys.argv or not on_accel

    if quick:
        cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2,
                        n_heads=4, max_seq=256, dtype=jnp.float32)
        batch, seq, steps = 4, 128, 4
    else:
        # Tuned single-chip recipe (profiled on v5e): unrolled layer
        # loop (scan residual stashing costs ~20%/step), no-remat CE
        # (backward reuses saved logits: one fewer full vocab matmul),
        # fused-backward 1024x1024 flash blocks, bf16 rope rotation,
        # batch 24 un-rematerialized.  steps=40 amortizes the ~100 ms
        # result-fetch latency of the axon tunnel out of the figure.
        cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                             dtype=jnp.bfloat16, remat=False,
                             unroll_layers=True, ce_chunk=-1)
        batch, seq, steps = 24, 1024, 40

    mesh = make_mesh(dp=len(devices), devices=devices)
    fns = training.build_gpt_train(cfg, mesh)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    batch_data = training.synthetic_lm_batch(
        jax.random.PRNGKey(1), batch, seq, cfg.vocab_size)

    # warmup / compile (float() forces a device round-trip: the axon
    # tunnel's block_until_ready does not actually block)
    for _ in range(2):
        state, metrics = fns["step_fn"](state, batch_data)
        float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = fns["step_fn"](state, batch_data)
    # fetching the last loss forces the whole state-dependency chain
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tok_s = steps * tokens_per_step / dt
    tok_s_chip = tok_s / len(devices)

    from ray_tpu.models.gpt import num_params
    n_params = num_params(state.params)
    flops_per_token = 6 * n_params
    tflops = tok_s_chip * flops_per_token / 1e12
    peak = _chip_peak(devices[0])

    result = {
        "metric": "gpt2_train_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tok_s_chip / H100_GPT2_TOKENS_PER_SEC, 4),
        "platform": platform,
        "n_devices": len(devices),
        "model_params": n_params,
        "achieved_tflops_per_chip": round(tflops, 2),
        "chip_peak_tflops": peak,
        "mfu": round(tflops / peak, 4),
        "final_loss": round(float(metrics["loss"]), 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
