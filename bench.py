"""Headline benchmark: GPT-2 (124M) training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference's north-star (BASELINE.json) is per-device training
throughput matching H100+NCCL.  Baseline constant below is the per-H100
GPT-2-small bf16 DDP throughput (~255k tokens/s/GPU ≈ 190 TFLOP/s
effective at 6*N FLOPs/token); vs_baseline = ours / that.  Measured on
whatever accelerator jax exposes (TPU chip under axon; CPU fallback for
smoke runs scales the model down).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

H100_GPT2_TOKENS_PER_SEC = 255_000.0

# bf16 peak of the chip families we may land on (for the MFU figure)
_CHIP_PEAK_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0, "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0, "v6e": 918.0,
}


def _chip_peak(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _CHIP_PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return 197.0


def _kernel_smoke():
    """Run the kernel numerics tests (CPU interpret mode) before paying
    for a chip run: a broken kernel should fail loudly here, not show
    up as a silent perf/loss regression.  Skips when pytest or the test
    tree is absent (wheel installs); ``RAY_TPU_BENCH_SMOKE=0`` opts out.
    """
    if os.environ.get("RAY_TPU_BENCH_SMOKE", "1") == "0":
        return
    try:
        import pytest  # noqa: F401
    except ImportError:
        return
    here = os.path.dirname(os.path.abspath(__file__))
    target = os.path.join(here, "tests", "test_ops.py")
    if not os.path.exists(target):
        return
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         target],
        cwd=here, env=env)
    if proc.returncode:
        print(json.dumps({"metric": "gpt2_train_tokens_per_sec_per_chip",
                          "error": "kernel smoke tests failed"}))
        sys.exit(proc.returncode)


def main():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    platform = devices[0].platform
    on_accel = platform not in ("cpu",)
    quick = "--quick" in sys.argv or not on_accel

    if quick:
        cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2,
                        n_heads=4, max_seq=256, dtype=jnp.float32)
        batch, seq, steps = 4, 128, 4
    else:
        # Tuned single-chip recipe (profiled on v5e): unrolled layer
        # loop (scan residual stashing costs ~20%/step), no-remat CE
        # (backward reuses saved logits: one fewer full vocab matmul),
        # fused-backward 1024x1024 flash blocks, bf16 rope rotation,
        # batch 24 un-rematerialized.  steps=40 amortizes the ~100 ms
        # result-fetch latency of the axon tunnel out of the figure.
        cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                             dtype=jnp.bfloat16, remat=False,
                             unroll_layers=True, ce_chunk=-1)
        batch, seq, steps = 24, 1024, 40

    if not quick:
        _kernel_smoke()

    from ray_tpu.ops.attention import uses_pack2
    mesh = make_mesh(dp=len(devices), devices=devices)
    # mirror of the kernel's own dispatch gate (head_dim/even heads/
    # tileability), so the reported field matches what actually runs
    attn_pack2 = uses_pack2(seq, seq, cfg.n_heads, cfg.head_dim)
    fns = training.build_gpt_train(cfg, mesh, attn_pack2=attn_pack2)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    batch_data = training.synthetic_lm_batch(
        jax.random.PRNGKey(1), batch, seq, cfg.vocab_size)

    # warmup / compile (float() forces a device round-trip: the axon
    # tunnel's block_until_ready does not actually block).  The packed
    # attention schedule is interpret-mode-tested by the preamble, but
    # a Mosaic compile failure on new hardware must degrade to the
    # single-head schedule loudly, not kill the headline number.
    try:
        for _ in range(2):
            state, metrics = fns["step_fn"](state, batch_data)
            float(metrics["loss"])
    except Exception as e:
        if not attn_pack2:
            raise
        print(f"pack2 schedule failed to compile/run ({e!r}); "
              f"falling back to single-head kernels", file=sys.stderr)
        attn_pack2 = False
        fns = training.build_gpt_train(cfg, mesh, attn_pack2=False)
        state = fns["init_fn"](jax.random.PRNGKey(0))
        for _ in range(2):
            state, metrics = fns["step_fn"](state, batch_data)
            float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = fns["step_fn"](state, batch_data)
    # fetching the last loss forces the whole state-dependency chain
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tok_s = steps * tokens_per_step / dt
    tok_s_chip = tok_s / len(devices)

    from ray_tpu.models.gpt import num_params
    n_params = num_params(state.params)
    flops_per_token = 6 * n_params
    tflops = tok_s_chip * flops_per_token / 1e12
    peak = _chip_peak(devices[0])

    result = {
        "metric": "gpt2_train_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tok_s_chip / H100_GPT2_TOKENS_PER_SEC, 4),
        "platform": platform,
        "n_devices": len(devices),
        "model_params": n_params,
        "achieved_tflops_per_chip": round(tflops, 2),
        "chip_peak_tflops": peak,
        "mfu": round(tflops / peak, 4),
        "final_loss": round(float(metrics["loss"]), 4),
        # which attention schedule the step actually ran (two-head lane
        # packing engages at head_dim 64 / even heads; false also if
        # the packed compile fell back above)
        "attn_pack2": attn_pack2,
    }
    print(json.dumps(result))

    if "--components" in sys.argv and not quick:
        # step-component view: attention fwd+bwd in isolation, packed
        # vs single-head, so a kernel A/B needs no xplane trace.  Skip
        # the packed arm when the step itself fell back (its compile
        # failure would re-raise here and eat the headline exit code).
        from ray_tpu._private.ray_perf import attention_perf
        arms = (True, False) if attn_pack2 else (False,)
        for pack2 in arms:
            comp = attention_perf(batch=batch, seq=seq,
                                  heads=cfg.n_heads,
                                  head_dim=cfg.head_dim, pack2=pack2)
            comp["metric"] = "attention_fwd_bwd"
            print(json.dumps(comp))


if __name__ == "__main__":
    main()
